// Example: load a jit.save'd paddle_tpu model and run one inference.
//
// Build (see go/README.md):
//   export CGO_LDFLAGS="-L$REPO/paddle_tpu/inference/csrc -lpaddle_tpu_capi \
//                       -L$(python3 -c 'import sysconfig;print(sysconfig.get_config_var(\"LIBDIR\"))') \
//                       -lpython3.12"
//   go build ./...
//   LD_LIBRARY_PATH=... ./example <model_prefix>
package main

import (
	"fmt"
	"os"

	"paddle_tpu/go/paddle"
)

func main() {
	if len(os.Args) < 2 {
		fmt.Fprintln(os.Stderr, "usage: example <model_prefix>")
		os.Exit(2)
	}
	cfg := paddle.NewAnalysisConfig()
	cfg.SetModel(os.Args[1], "")

	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		panic(err)
	}
	defer pred.Delete()

	in, err := paddle.NewTensor(make([]float32, 1*1*28*28),
		[]int64{1, 1, 28, 28})
	if err != nil {
		panic(err)
	}
	outs, err := pred.Run([]*paddle.Tensor{in})
	if err != nil {
		panic(err)
	}
	for i, o := range outs {
		fmt.Printf("output %d shape=%v first=%v\n", i, o.Shape,
			o.Data[0])
	}
}
