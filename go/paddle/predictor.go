package paddle

// Reference: go/paddle/predictor.go — NewPredictor / Run / outputs over
// the C inference ABI.  This binding targets the paddle_tpu C ABI
// (paddle_tpu_capi.h): PT_NewPredictor / PT_PredictorRun / PT_GetOutput.
//
// cgo pointer discipline: the PT_PredictorRun signature takes arrays of
// pointers; Go pointers may not be stored into C-visible memory, so
// input buffers and the pointer tables are staged in C allocations for
// the duration of the call (the reference binding copies at the
// ZeroCopyTensor boundary the same way).

/*
#cgo CFLAGS: -I${SRCDIR}/../../paddle_tpu/inference/csrc
#cgo LDFLAGS: -L${SRCDIR}/../../paddle_tpu/inference/csrc -lpaddle_tpu_capi
#include <stdlib.h>
#include <string.h>
#include "paddle_tpu_capi.h"
*/
import "C"

import (
	"errors"
	"fmt"
	"runtime"
	"unsafe"
)

// Predictor wraps a loaded model.
type Predictor struct {
	ptr *C.PT_Predictor
}

// NewPredictor loads the jit.save'd model named by config.ModelDir().
func NewPredictor(config *Config) (*Predictor, error) {
	cs := C.CString(config.ModelDir())
	defer C.free(unsafe.Pointer(cs))
	p := C.PT_NewPredictor(cs)
	if p == nil {
		return nil, fmt.Errorf("paddle: loading %q failed (see stderr)",
			config.ModelDir())
	}
	pred := &Predictor{ptr: p}
	runtime.SetFinalizer(pred, func(pr *Predictor) { pr.Delete() })
	return pred, nil
}

// Delete releases the predictor (reference: DeletePredictor).
func (p *Predictor) Delete() {
	if p.ptr != nil {
		C.PT_DeletePredictor(p.ptr)
		p.ptr = nil
	}
}

// Run executes the model on float32 inputs and returns all outputs.
func (p *Predictor) Run(inputs []*Tensor) ([]*Tensor, error) {
	if p.ptr == nil {
		return nil, errors.New("paddle: predictor deleted")
	}
	n := len(inputs)
	if n == 0 {
		return nil, errors.New("paddle: no inputs")
	}

	// stage inputs in C memory (see cgo note above)
	dataPtrs := C.malloc(C.size_t(n) * C.size_t(unsafe.Sizeof(uintptr(0))))
	shapePtrs := C.malloc(C.size_t(n) * C.size_t(unsafe.Sizeof(uintptr(0))))
	ndims := C.malloc(C.size_t(n) * C.size_t(unsafe.Sizeof(C.int32_t(0))))
	defer C.free(dataPtrs)
	defer C.free(shapePtrs)
	defer C.free(ndims)
	dataTab := (*[1 << 28]unsafe.Pointer)(dataPtrs)[:n:n]
	shapeTab := (*[1 << 28]unsafe.Pointer)(shapePtrs)[:n:n]
	ndimTab := (*[1 << 28]C.int32_t)(ndims)[:n:n]

	for i, t := range inputs {
		nb := C.size_t(len(t.Data)) * 4
		buf := C.malloc(nb)
		defer C.free(buf)
		if len(t.Data) > 0 {
			C.memcpy(buf, unsafe.Pointer(&t.Data[0]), nb)
		}
		sb := C.malloc(C.size_t(len(t.Shape)) * 8)
		defer C.free(sb)
		if len(t.Shape) > 0 {
			C.memcpy(sb, unsafe.Pointer(&t.Shape[0]),
				C.size_t(len(t.Shape))*8)
		}
		dataTab[i] = buf
		shapeTab[i] = sb
		ndimTab[i] = C.int32_t(len(t.Shape))
	}

	nOut := C.PT_PredictorRun(p.ptr,
		(**C.float)(dataPtrs), (**C.int64_t)(shapePtrs),
		(*C.int32_t)(ndims), C.int32_t(n))
	if nOut < 0 {
		return nil, errors.New("paddle: PT_PredictorRun failed")
	}

	outs := make([]*Tensor, int(nOut))
	for i := range outs {
		var raw C.PT_Output
		if C.PT_GetOutput(p.ptr, C.int32_t(i), &raw) != 0 {
			// the implementation may have allocated shape before
			// failing; PT_FreeOutput is null-safe
			C.PT_FreeOutput(&raw)
			return nil, fmt.Errorf("paddle: PT_GetOutput(%d) failed", i)
		}
		shape := make([]int64, int(raw.ndim))
		if raw.ndim > 0 {
			src := (*[1 << 28]C.int64_t)(unsafe.Pointer(raw.shape))
			for d := range shape {
				shape[d] = int64(src[d])
			}
		}
		data := make([]float32, int(raw.numel))
		if raw.numel > 0 {
			src := (*[1 << 28]C.float)(unsafe.Pointer(raw.data))
			for j := range data {
				data[j] = float32(src[j])
			}
		}
		C.PT_FreeOutput(&raw)
		outs[i] = &Tensor{Data: data, Shape: shape}
	}
	return outs, nil
}
