// Package paddle: Go inference binding over the paddle_tpu C ABI.
//
// Reference: go/paddle/config.go (AnalysisConfig over paddle_inference_c).
// TPU-native differences: XLA owns device selection and graph
// optimization, so the GPU/TensorRT/IR-pass knobs either no-op
// truthfully (documented per method) or don't exist; the model is a
// jit.save'd path prefix.
package paddle

// Config mirrors the reference AnalysisConfig surface that remains
// meaningful here: model location.
type Config struct {
	modelPrefix string
}

// NewConfig returns an empty config (reference: NewAnalysisConfig).
func NewConfig() *Config { return &Config{} }

// AnalysisConfig is the reference-compatible alias.
type AnalysisConfig = Config

// NewAnalysisConfig matches the reference constructor name.
func NewAnalysisConfig() *AnalysisConfig { return NewConfig() }

// SetModel points at a jit.save'd model. The reference takes
// (model_file, params_file); here one prefix addresses both artifacts,
// and a non-empty params argument is ignored (single-file format).
func (c *Config) SetModel(modelPrefix string, params string) {
	c.modelPrefix = modelPrefix
}

// ModelDir returns the configured model prefix.
func (c *Config) ModelDir() string { return c.modelPrefix }

// ProgFile returns the model prefix (single-artifact format).
func (c *Config) ProgFile() string { return c.modelPrefix }

// ParamsFile returns the model prefix (single-artifact format).
func (c *Config) ParamsFile() string { return c.modelPrefix }

// DisableGpu is a truthful no-op: device placement belongs to XLA.
func (c *Config) DisableGpu() {}

// UseGpu always reports false: there is no CUDA path in this runtime.
func (c *Config) UseGpu() bool { return false }

// SwitchIrOptim is a truthful no-op: XLA always optimizes.
func (c *Config) SwitchIrOptim(bool) {}

// IrOptim reports true: compilation always optimizes (XLA).
func (c *Config) IrOptim() bool { return true }
