package paddle

// Reference: go/paddle/tensor.go (ZeroCopyTensor). The C ABI carries
// float32 data + int64 shapes; Tensor is the Go-side value.

import "fmt"

// Tensor is a dense float32 array with an int64 shape.
type Tensor struct {
	Data  []float32
	Shape []int64
}

// ZeroCopyTensor is the reference-compatible alias (the ABI copies at
// the boundary; the name is kept for drop-in source compatibility).
type ZeroCopyTensor = Tensor

// NewTensor builds a tensor, validating that len(data) matches shape.
func NewTensor(data []float32, shape []int64) (*Tensor, error) {
	n := int64(1)
	for _, d := range shape {
		n *= d
	}
	if int64(len(data)) != n {
		return nil, fmt.Errorf(
			"paddle: data has %d elements, shape %v needs %d",
			len(data), shape, n)
	}
	return &Tensor{Data: data, Shape: shape}, nil
}

// SetValue replaces the tensor's contents (reference: SetValue).
func (t *Tensor) SetValue(data []float32, shape []int64) error {
	nt, err := NewTensor(data, shape)
	if err != nil {
		return err
	}
	*t = *nt
	return nil
}

// Value returns the data slice (reference: Value interface{}).
func (t *Tensor) Value() []float32 { return t.Data }

// Numel returns the element count.
func (t *Tensor) Numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}
