"""Benchmark harness — BERT-base-shaped masked-LM pretraining step.

Run:  python bench.py [--steps N] [--profile DIR] [--small]

Prints ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

The flagship config matches BASELINE.json configs[2-3] (BERT-base /
ERNIE-1.0 shapes: L=12, H=768, A=12, FF=3072, seq=512).  The whole train
step — forward, backward, AdamW update, global-norm clip — is ONE compiled
XLA program with donated buffers (paddle_tpu.jit.TrainStep), bf16 compute
with fp32 master weights.  vs_baseline is measured MFU / 0.35 (the
BASELINE.json north-star floor of 35% MFU).
"""
import argparse
import json
import sys
import time

import numpy as np


# bf16 peak FLOPs/s per chip by device kind (public specs)
_PEAK = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for k, v in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if k.lower() in kind.lower():
            return v
    return 0.0  # unknown (CPU): MFU not defined


def build_model(vocab, hidden, layers, heads, ffn, seq, dropout):
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class BertMLM(nn.Layer):
        """BERT-base-shaped encoder LM (reference shapes:
        nn/layer/transformer.py TransformerEncoder; PaddleNLP bert-base)."""

        def __init__(self):
            super().__init__()
            self.tok = nn.Embedding(vocab, hidden)
            self.pos = nn.Embedding(seq, hidden)
            enc = nn.TransformerEncoderLayer(
                hidden, heads, ffn, dropout=dropout, activation="gelu",
                attn_dropout=dropout, act_dropout=dropout)
            self.encoder = nn.TransformerEncoder(enc, layers)
            self.norm = nn.LayerNorm(hidden)
            self.head = nn.Linear(hidden, vocab)

        def forward(self, ids):
            pos_ids = paddle.arange(ids.shape[1]).unsqueeze(0)
            x = self.tok(ids) + self.pos(pos_ids)
            x = self.encoder(x)
            return self.head(self.norm(x))

    return BertMLM()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--profile", type=str, default=None,
                    help="directory for a jax profiler trace of timed steps")
    ap.add_argument("--small", action="store_true",
                    help="force the tiny CPU config")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" and not args.small

    if on_tpu:
        cfg = dict(vocab=30522, hidden=768, layers=12, heads=12, ffn=3072,
                   seq=512, batch=64, dropout=0.1, attn_dropout=0.1)
        steps = args.steps or 20
        dtype = "bfloat16"
    else:
        cfg = dict(vocab=1000, hidden=128, layers=2, heads=4, ffn=512,
                   seq=128, batch=8, dropout=0.1, attn_dropout=0.1)
        steps = args.steps or 5
        dtype = "float32"

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm

    paddle.seed(2024)
    model = build_model(cfg["vocab"], cfg["hidden"], cfg["layers"],
                        cfg["heads"], cfg["ffn"], cfg["seq"], cfg["dropout"])
    opt = optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), weight_decay=0.01,
        grad_clip=ClipGradByGlobalNorm(1.0),
        multi_precision=(dtype != "float32"))
    if dtype != "float32":
        model, opt = amp.decorate(model, opt, level="O2", dtype=dtype)

    def loss_fn(out, labels):
        return F.cross_entropy(out.reshape([-1, cfg["vocab"]]),
                               labels.reshape([-1]))

    step = TrainStep(model, loss_fn, opt, n_inputs=1, donate=True)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))
    y = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))

    for _ in range(max(args.warmup, 1)):  # >=1: compile outside timed region
        loss = step(x, y)
    float(loss)  # sync

    prof = None
    if args.profile:
        jax.profiler.start_trace(args.profile)
        prof = args.profile
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    last = float(loss)  # device sync
    dt = time.perf_counter() - t0
    if prof:
        jax.profiler.stop_trace()

    steps_per_sec = steps / dt
    tokens = cfg["batch"] * cfg["seq"]
    tokens_per_sec = tokens * steps_per_sec

    # model FLOPs: 6*N*T for matmuls (fwd+bwd) + 12*L*B*S^2*H attention
    # scores/values (PaLM appendix-B accounting)
    n_params = sum(int(np.prod(p.shape_tuple)) for p in model.parameters())
    n_embed = cfg["vocab"] * cfg["hidden"] + cfg["seq"] * cfg["hidden"]
    n_dense = n_params - n_embed
    flops_per_step = (6 * n_dense * tokens
                      + 12 * cfg["layers"] * cfg["batch"]
                      * cfg["seq"] ** 2 * cfg["hidden"])
    achieved = flops_per_step * steps_per_sec
    peak = _peak_flops(dev)
    mfu = achieved / peak if peak else 0.0

    result = {
        "metric": ("bert_base_pretrain_tokens_per_sec_per_chip" if on_tpu
                   else "bert_tiny_cpu_smoke_tokens_per_sec"),
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
        "mfu": round(mfu, 4),
        "steps_per_sec": round(steps_per_sec, 4),
        "step_time_ms": round(1000 * dt / steps, 2),
        "model_flops_per_step": flops_per_step,
        "final_loss": round(last, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "platform": dev.platform,
        "config": cfg,
        "dtype": dtype,
        "donated": True,
        "profile_dir": prof,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
