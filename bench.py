"""Benchmark harness for the BASELINE.json graded configs.

Run:  python bench.py [--steps N] [--profile DIR] [--small] [--suite S]

Prints ONE JSON line on stdout.  The primary metric is the flagship
BERT-base masked-LM pretraining step (BASELINE.json configs[2-3]: L=12,
H=768, A=12, FF=3072, seq=512); secondary suite results (ResNet-50 conv
path, configs[1]; LeNet dygraph smoke, configs[0]) are embedded under
``"extra"`` in the same line.

Every compiled benchmark runs the whole train step — forward, backward,
optimizer update, clip — as ONE donated-buffer XLA program
(paddle_tpu.jit.TrainStep), bf16 compute with fp32 master weights.
vs_baseline is measured MFU / 0.35 (the BASELINE.json north-star floor).
"""
import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


# bf16 peak FLOPs/s per chip by device kind (public specs)
_PEAK = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}

# ResNet-50 v1.5 @224x224: ~4.09 GFLOP/image forward (standard accounting);
# training step counted as 3x forward (fwd + 2x bwd)
_RESNET50_FWD_FLOPS = 4.089e9


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for k, v in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if k.lower() in kind.lower():
            return v
    return 0.0  # unknown (CPU): MFU not defined


def build_model(vocab, hidden, layers, heads, ffn, seq, dropout):
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class BertMLM(nn.Layer):
        """BERT-base-shaped encoder LM (reference shapes:
        nn/layer/transformer.py TransformerEncoder; PaddleNLP bert-base).
        Forward returns the normalized hidden states; the vocab
        projection fuses into the loss (F.linear_cross_entropy) so the
        [tokens, vocab] logits never materialize."""

        def __init__(self):
            super().__init__()
            self.tok = nn.Embedding(vocab, hidden)
            self.pos = nn.Embedding(seq, hidden)
            enc = nn.TransformerEncoderLayer(
                hidden, heads, ffn, dropout=dropout, activation="gelu",
                attn_dropout=dropout, act_dropout=dropout)
            self.encoder = nn.TransformerEncoder(enc, layers)
            self.norm = nn.LayerNorm(hidden)
            self.head = nn.Linear(hidden, vocab)

        def forward(self, ids):
            pos_ids = paddle.arange(ids.shape[1]).unsqueeze(0)
            x = self.tok(ids) + self.pos(pos_ids)
            x = self.encoder(x)
            return self.norm(x)

    return BertMLM()


# Transient tunnel/RPC failure markers (round-4 postmortem: the driver's
# bench run died on "remote_compile: read body: response body closed" —
# a one-shot tunnel hiccup, not a code bug).  Any bench attempt that dies
# with one of these is retried from scratch (fresh model/optimizer state:
# donated buffers may be invalidated by a failed dispatch).
_TRANSIENT_MARKERS = (
    "remote_compile", "read body", "response body closed", "UNAVAILABLE",
    "DEADLINE_EXCEEDED", "Connection reset", "Socket closed",
)


def _is_transient(exc) -> bool:
    s = f"{type(exc).__name__}: {exc}"
    return any(m.lower() in s.lower() for m in _TRANSIENT_MARKERS)


def _retry_bench(fn, *args, attempts=3):
    """Run a whole bench function, retrying on transient tunnel errors.

    Retries rebuild the model from scratch: after a failed dispatch the
    donated input buffers of the in-flight step are in an undefined
    state, so resuming the same step loop is unsound.

    Every suite's result embeds the monitor-counter DELTA its run
    produced (``monitor_counters``: compile counts, pad hits, fs/batch
    retries, ...) so a BENCH_r0*.json trajectory explains a perf delta
    — "0.8x because 40 recompiles" — instead of just reporting it."""
    from paddle_tpu.utils import monitor
    for i in range(attempts):
        before = monitor.all_stats()
        try:
            res = fn(*args)
            if isinstance(res, dict):
                after = monitor.all_stats()
                delta = {k: after[k] - before.get(k, 0)
                         for k in sorted(after)
                         if after[k] != before.get(k, 0)}
                res["monitor_counters"] = delta
            return res
        except Exception as e:  # noqa: BLE001 - classify then re-raise
            if i == attempts - 1 or not _is_transient(e):
                raise
            sys.stderr.write(
                f"[bench] transient failure (attempt {i + 1}/{attempts}), "
                f"retrying: {type(e).__name__}: {e}\n")
            time.sleep(3.0 * (i + 1))


def _timed_steps(step, feeds, warmup, steps, profile_dir=None):
    for _ in range(max(warmup, 1)):  # >=1: compile outside timed region
        loss = step(*feeds)
    float(loss)  # sync
    if profile_dir:
        import jax
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    if profile_dir:
        from paddle_tpu.profiler import RecordEvent
        for i in range(steps):
            with RecordEvent(f"train_step#{i}"):  # named host-track span
                loss = step(*feeds)
    else:  # unprofiled timing: no annotation overhead in the numbers
        for _ in range(steps):
            loss = step(*feeds)
    last = float(loss)  # device sync
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    return dt, last


def bench_bert(args, dev, on_tpu):
    import jax
    import jax.numpy as jnp

    if on_tpu:
        cfg = dict(vocab=30522, hidden=768, layers=12, heads=12, ffn=3072,
                   seq=512,
                   batch=int(os.environ.get("BENCH_BERT_BATCH", "64")),
                   dropout=0.1, attn_dropout=0.1)
        steps = args.steps or 20
        dtype = "bfloat16"
    else:
        cfg = dict(vocab=1000, hidden=128, layers=2, heads=4, ffn=512,
                   seq=128, batch=8, dropout=0.1, attn_dropout=0.1)
        steps = args.steps or 5
        dtype = "float32"

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import amp, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm

    paddle.seed(2024)
    model = build_model(cfg["vocab"], cfg["hidden"], cfg["layers"],
                        cfg["heads"], cfg["ffn"], cfg["seq"], cfg["dropout"])
    opt = optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), weight_decay=0.01,
        grad_clip=ClipGradByGlobalNorm(1.0),
        multi_precision=(dtype != "float32"))
    if dtype != "float32":
        model, opt = amp.decorate(model, opt, level="O2", dtype=dtype)

    def loss_fn(out, labels):
        # fused chunked head+CE: same math as
        # cross_entropy(head(out), labels), logits stay chunk-local
        return F.linear_cross_entropy(
            out.reshape([-1, cfg["hidden"]]), model.head.weight,
            model.head.bias, labels.reshape([-1]))

    step = TrainStep(model, loss_fn, opt, n_inputs=1, donate=True)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))
    y = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))

    prof = args.profile or None
    dt, last = _timed_steps(step, (x, y), args.warmup, steps,
                            profile_dir=prof)

    steps_per_sec = steps / dt
    tokens = cfg["batch"] * cfg["seq"]

    # model FLOPs: 6*N*T for matmuls (fwd+bwd) + 12*L*B*S^2*H attention
    # scores/values (PaLM appendix-B accounting)
    n_params = sum(int(np.prod(p.shape_tuple)) for p in model.parameters())
    n_embed = cfg["vocab"] * cfg["hidden"] + cfg["seq"] * cfg["hidden"]
    n_dense = n_params - n_embed
    flops_per_step = (6 * n_dense * tokens
                      + 12 * cfg["layers"] * cfg["batch"]
                      * cfg["seq"] ** 2 * cfg["hidden"])
    peak = _peak_flops(dev)
    mfu = flops_per_step * steps_per_sec / peak if peak else 0.0

    return {
        "metric": ("bert_base_pretrain_tokens_per_sec_per_chip" if on_tpu
                   else "bert_tiny_cpu_smoke_tokens_per_sec"),
        "value": round(tokens * steps_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
        "mfu": round(mfu, 4),
        "steps_per_sec": round(steps_per_sec, 4),
        "step_time_ms": round(1000 * dt / steps, 2),
        "model_flops_per_step": flops_per_step,
        "final_loss": round(last, 4),
        "config": cfg,
        "dtype": dtype,
        "donated": True,
        "profile_dir": prof,
    }


def build_gpt(vocab, hidden, layers, heads, ffn, seq, dropout):
    """GPT-shaped causal decoder LM (BASELINE.json configs[4] single-chip
    proxy; reference shapes: PaddleNLP gpt/modeling.py, fed by the fleet
    hybrid runtime section_worker.cc:128-165).  Pre-norm blocks, tied
    input/output embedding (the vocab projection reuses ``tok.weight`` via
    the fused chunked linear_cross_entropy loss), causal Pallas flash
    attention."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    import paddle_tpu.nn.functional as F

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(hidden)
            self.q = nn.Linear(hidden, hidden)
            self.k = nn.Linear(hidden, hidden)
            self.v = nn.Linear(hidden, hidden)
            self.proj = nn.Linear(hidden, hidden)
            self.ln2 = nn.LayerNorm(hidden)
            self.fc1 = nn.Linear(hidden, ffn)
            self.fc2 = nn.Linear(ffn, hidden)
            self.drop = nn.Dropout(dropout)

        def forward(self, x):
            B, S = x.shape[0], x.shape[1]
            h = self.ln1(x)
            hd = hidden // heads
            q = self.q(h).reshape([B, S, heads, hd])
            k = self.k(h).reshape([B, S, heads, hd])
            v = self.v(h).reshape([B, S, heads, hd])
            a = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=dropout,
                training=self.training)
            x = x + self.drop(self.proj(a.reshape([B, S, hidden])))
            h = self.ln2(x)
            x = x + self.drop(self.fc2(F.gelu(self.fc1(h),
                                              approximate=True)))
            return x

    # GPT-2 init: N(0, 0.02) embeddings — with the tied head this keeps
    # initial logits O(1) (paddle default N(0,1) embeddings would give
    # CE ~ 10x ln(V) at step 0 through the tied projection)
    emb_attr = paddle.ParamAttr(
        initializer=nn.initializer.Normal(0.0, 0.02))

    class GPT(nn.Layer):
        def __init__(self):
            super().__init__()
            self.tok = nn.Embedding(vocab, hidden, weight_attr=emb_attr)
            self.pos = nn.Embedding(seq, hidden, weight_attr=emb_attr)
            self.drop = nn.Dropout(dropout)
            self.blocks = nn.LayerList([Block() for _ in range(layers)])
            self.ln_f = nn.LayerNorm(hidden)

        def forward(self, ids):
            from paddle_tpu.parallel import recompute
            pos_ids = paddle.arange(ids.shape[1]).unsqueeze(0)
            x = self.drop(self.tok(ids) + self.pos(pos_ids))
            for blk in self.blocks:
                # per-block remat: peak bwd memory = one block's
                # internals + per-block boundary activations (whole-model
                # jax.checkpoint would keep every layer's temps live in
                # one rematted backward — measured 21.8 GB at 760M)
                x = recompute(blk, x)
            return self.ln_f(x)

    return GPT()


# single-chip GPT presets: "largest that fits" on a 16 GB v5e with fp32
# AdamW state (param bf16 2B + master 4B + m 4B + v 4B = 14 B/param).
# 1.3B proper (H=2048 L=24) needs 18.4 GB of state alone — does not fit
# one chip; 760M-class is the largest standard GPT size that leaves
# activation/workspace headroom.  BASELINE configs[4] runs 1.3B across a
# pod; the multi-chip sharding for that is exercised in
# __graft_entry__.dryrun_multichip.
_GPT_PRESETS = {
    "760m": dict(vocab=50257, hidden=1536, layers=24, heads=16, ffn=6144,
                 seq=1024, dropout=0.1),
    "1b": dict(vocab=50257, hidden=1792, layers=24, heads=14, ffn=7168,
               seq=1024, dropout=0.1),
}


def bench_gpt(args, dev, on_tpu):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import amp, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm

    if on_tpu:
        preset = os.environ.get("BENCH_GPT_PRESET", "760m")
        cfg = dict(_GPT_PRESETS[preset],
                   batch=int(os.environ.get("BENCH_GPT_BATCH", "16")))
        steps = args.steps or 10
        dtype = "bfloat16"
    else:
        preset = "cpu_smoke"
        cfg = dict(vocab=1000, hidden=128, layers=2, heads=4, ffn=512,
                   seq=128, dropout=0.1, batch=4)
        steps = args.steps or 3
        dtype = "float32"

    paddle.seed(2024)
    model = build_gpt(cfg["vocab"], cfg["hidden"], cfg["layers"],
                      cfg["heads"], cfg["ffn"], cfg["seq"], cfg["dropout"])
    opt = optimizer.AdamW(
        learning_rate=2e-4, parameters=model.parameters(), weight_decay=0.01,
        grad_clip=ClipGradByGlobalNorm(1.0),
        multi_precision=(dtype != "float32"))
    if dtype != "float32":
        model, opt = amp.decorate(model, opt, level="O2", dtype=dtype)

    def loss_fn(out, labels):
        # tied head: logits = out @ tok.weight^T, fused+chunked so the
        # [tokens, 50257] logits never materialize
        w = paddle.transpose(model.tok.weight, [1, 0])
        bias = paddle.zeros([cfg["vocab"]], dtype=w.dtype)
        return F.linear_cross_entropy(
            out.reshape([-1, cfg["hidden"]]), w, bias, labels.reshape([-1]))

    step = TrainStep(model, loss_fn, opt, n_inputs=1, donate=True)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))
    y = jnp.asarray(rng.randint(0, cfg["vocab"],
                                (cfg["batch"], cfg["seq"]), dtype=np.int32))

    # profile only when gpt is the selected suite (under --suite all the
    # trace dir belongs to the flagship bert run)
    prof = args.profile if args.suite == "gpt" else None
    dt, last = _timed_steps(step, (x, y), args.warmup, steps,
                            profile_dir=prof)
    steps_per_sec = steps / dt
    tokens = cfg["batch"] * cfg["seq"]

    n_params = sum(int(np.prod(p.shape_tuple)) for p in model.parameters())
    n_embed = (cfg["vocab"] + cfg["seq"]) * cfg["hidden"]
    # dense matmul FLOPs: the tied vocab projection does a real
    # [T,H]x[H,V] matmul in the loss, so add it back to the dense count;
    # causal attention does half the S^2 work (flash skips masked blocks)
    n_matmul = (n_params - n_embed) + cfg["vocab"] * cfg["hidden"]
    flops_per_step = (6 * n_matmul * tokens
                      + 6 * cfg["layers"] * cfg["batch"]
                      * cfg["seq"] ** 2 * cfg["hidden"])
    peak = _peak_flops(dev)
    mfu = flops_per_step * steps_per_sec / peak if peak else 0.0

    return {
        "metric": (f"gpt_{preset}_pretrain_tokens_per_sec_per_chip"
                   if on_tpu else "gpt_tiny_cpu_smoke_tokens_per_sec"),
        "value": round(tokens * steps_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if peak else 0.0,
        "mfu": round(mfu, 4),
        "steps_per_sec": round(steps_per_sec, 4),
        "step_time_ms": round(1000 * dt / steps, 2),
        "model_flops_per_step": flops_per_step,
        "n_params": n_params,
        "final_loss": round(last, 4),
        "config": cfg,
        "dtype": dtype,
        "recompute": "per_block",
        "tied_embedding": True,
        "flops_accounting": "6*N*T dense (+tied head) + causal attn S^2/2",
        "note": ("single-chip proxy of BASELINE configs[4]; 1.3B optimizer "
                 "state (18.4 GB fp32 AdamW) exceeds one 16 GB chip — "
                 "largest-that-fits preset; pod-scale hybrid sharding "
                 "exercised in dryrun_multichip"),
    }


def build_bert_static(vocab, hidden, layers, heads, ffn, seq, batch,
                      seed=2024):
    """Record a BERT-shaped encoder masked-LM *static* training program
    (post-norm blocks, no dropout): the op chains the cost model ranks
    as fusion candidates — linear+gelu in the FFN, linear+add+layer_norm
    around each residual — exactly what the executor's Pallas
    epilogue-fusion pass realizes.  Static batch dim: the Executor
    compiles per feed signature anyway, and concrete avals let
    Program.analyze gate the kernels without a batch_size hint.
    Returns (program, loss_var, feeds_builder)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn, optimizer

    paddle.seed(seed)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        ids = paddle.static.data("ids", [batch, seq], "int64")
        labels = paddle.static.data("labels", [batch, seq], "int64")
        tok = nn.Embedding(vocab, hidden)
        pos = nn.Embedding(seq, hidden)
        x = tok(ids) + pos(paddle.arange(seq).unsqueeze(0))
        hd = hidden // heads
        for _ in range(layers):
            wq = nn.Linear(hidden, hidden)
            wk = nn.Linear(hidden, hidden)
            wv = nn.Linear(hidden, hidden)
            proj = nn.Linear(hidden, hidden)
            ln1 = nn.LayerNorm(hidden)
            fc1 = nn.Linear(hidden, ffn)
            fc2 = nn.Linear(ffn, hidden)
            ln2 = nn.LayerNorm(hidden)
            q = wq(x).reshape([batch, seq, heads, hd])
            k = wk(x).reshape([batch, seq, heads, hd])
            v = wv(x).reshape([batch, seq, heads, hd])
            a = F.scaled_dot_product_attention(q, k, v)
            # linear+add+layer_norm chain (residual epilogue)
            x = ln1(proj(a.reshape([batch, seq, hidden])) + x)
            # linear+gelu chain (FFN epilogue)
            h = F.gelu(fc1(x), approximate=True)
            x = ln2(fc2(h) + x)
        head = nn.Linear(hidden, vocab)
        logits = head(x)
        loss = F.cross_entropy(logits.reshape([-1, vocab]),
                               labels.reshape([-1]))
        optimizer.Adam(learning_rate=1e-4).minimize(loss)

    def feeds(rng):
        return {
            "ids": jnp.asarray(rng.randint(
                0, vocab, (batch, seq), dtype=np.int64)),
            "labels": jnp.asarray(rng.randint(
                0, vocab, (batch, seq), dtype=np.int64)),
        }

    return main, loss, feeds


def build_gpt_static(vocab, hidden, layers, heads, ffn, seq, batch,
                     seed=2024):
    """GPT-shaped causal decoder LM as a static training program
    (pre-norm blocks, no dropout, untied head): the residual adds after
    ``proj``/``fc2`` and the ``fc1``+gelu FFN are the realized chains.
    Returns (program, loss_var, feeds_builder)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn, optimizer

    paddle.seed(seed)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        ids = paddle.static.data("ids", [batch, seq], "int64")
        labels = paddle.static.data("labels", [batch, seq], "int64")
        tok = nn.Embedding(vocab, hidden)
        pos = nn.Embedding(seq, hidden)
        x = tok(ids) + pos(paddle.arange(seq).unsqueeze(0))
        hd = hidden // heads
        for _ in range(layers):
            ln1 = nn.LayerNorm(hidden)
            wq = nn.Linear(hidden, hidden)
            wk = nn.Linear(hidden, hidden)
            wv = nn.Linear(hidden, hidden)
            proj = nn.Linear(hidden, hidden)
            ln2 = nn.LayerNorm(hidden)
            fc1 = nn.Linear(hidden, ffn)
            fc2 = nn.Linear(ffn, hidden)
            h = ln1(x)
            q = wq(h).reshape([batch, seq, heads, hd])
            k = wk(h).reshape([batch, seq, heads, hd])
            v = wv(h).reshape([batch, seq, heads, hd])
            a = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            # linear+add chain (residual epilogue on the projection)
            x = proj(a.reshape([batch, seq, hidden])) + x
            h = ln2(x)
            x = fc2(F.gelu(fc1(h), approximate=True)) + x
        lnf = nn.LayerNorm(hidden)
        head = nn.Linear(hidden, vocab)
        logits = head(lnf(x))
        loss = F.cross_entropy(logits.reshape([-1, vocab]),
                               labels.reshape([-1]))
        optimizer.Adam(learning_rate=1e-4).minimize(loss)

    def feeds(rng):
        return {
            "ids": jnp.asarray(rng.randint(
                0, vocab, (batch, seq), dtype=np.int64)),
            "labels": jnp.asarray(rng.randint(
                0, vocab, (batch, seq), dtype=np.int64)),
        }

    return main, loss, feeds


def bench_resnet50(args, dev, on_tpu):
    """Conv-path benchmark (BASELINE.json configs[1]): ResNet-50, synthetic
    ImageNet shapes, SGD+momentum, bf16 with fp32 master weights."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import amp, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch, hw, steps, dtype = 128, 224, (args.steps or 20), "bfloat16"
    else:
        batch, hw, steps, dtype = 4, 64, (args.steps or 3), "float32"
    # NCHW vs NHWC measure identically on v5e (XLA's layout assignment
    # normalizes conv layouts); keep the paddle-default NCHW
    data_format = os.environ.get("BENCH_RESNET_FORMAT", "NCHW").upper()
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"BENCH_RESNET_FORMAT must be NCHW or NHWC, "
                         f"got {data_format!r}")

    paddle.seed(2024)
    model = resnet50(data_format=data_format)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters(),
                             multi_precision=(dtype != "float32"))
    if dtype != "float32":
        model, opt = amp.decorate(model, opt, level="O2", dtype=dtype)

    def loss_fn(out, labels):
        return F.cross_entropy(out, labels)

    step = TrainStep(model, loss_fn, opt, n_inputs=1, donate=True)
    rng = np.random.RandomState(0)
    shape = ((batch, hw, hw, 3) if data_format == "NHWC"
             else (batch, 3, hw, hw))
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    if dtype != "float32":
        x = x.astype(jnp.bfloat16)  # bf16 input pipeline, standard on TPU
    y = jnp.asarray(rng.randint(0, 1000, (batch,), dtype=np.int64))

    dt, last = _timed_steps(step, (x, y), args.warmup, steps)
    steps_per_sec = steps / dt
    imgs_per_sec = batch * steps_per_sec
    flops_per_step = 3 * _RESNET50_FWD_FLOPS * batch if hw == 224 else 0
    peak = _peak_flops(dev)
    mfu = flops_per_step * steps_per_sec / peak if peak else 0.0
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/s/chip",
        "mfu": round(mfu, 4),
        "step_time_ms": round(1000 * dt / steps, 2),
        "batch": batch,
        "image_size": hw,
        "data_format": data_format,
        "dtype": dtype,
        "flops_accounting": "3 x 4.089 GF/img (fwd x3 train)",
        "final_loss": round(last, 4),
    }


def _timed_static_loop(exe, main, loss, feed, steps, warmup=3):
    """Warmup (compile) + timed async loop (return_numpy=False, one sync
    at the end); returns (dt, last_loss)."""
    def step():
        return exe.run(main, feed=feed, fetch_list=[loss],
                       return_numpy=False)[0]
    for _ in range(max(warmup, 1)):
        last = step()
    float(np.asarray(last.data))
    t0 = time.perf_counter()
    for _ in range(steps):
        last = step()
    lv = float(np.asarray(last.data))
    return time.perf_counter() - t0, lv


def bench_static(args, dev, on_tpu):
    """Static-graph Executor hot path (ISSUE 2 tentpole): donated
    device-resident async dispatch, measured against the preserved
    pre-change host-loop path (Executor._run_legacy) on the SAME config.

    Two entries: ``static_mlp`` — the hot-path micro where per-step host
    work (feed NumPy round-trip, per-param write-back, scalar uploads,
    fetch sync) is comparable to device compute, so the speedup of the
    redesign is directly visible; ``static_lenet`` — the conv net from
    the tier-1 suite, tracking absolute static-path steps/sec and the
    compile count (must be 1 per feed signature)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.vision.models import LeNet

    if on_tpu:
        hidden, depth, batch, steps = 1024, 8, 256, (args.steps or 100)
        lenet_batch, lenet_steps = 256, (args.steps or 50)
    else:
        # deep-and-narrow: per-step host work (feeds, write-back, scalar
        # uploads, sync) is comparable to device compute, so the hot-path
        # redesign is visible above CPU timer noise
        hidden, depth, batch, steps = 128, 8, 32, (args.steps or 150)
        lenet_batch, lenet_steps = 16, (args.steps or 30)

    def build_mlp(seed):
        paddle.seed(seed)
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, hidden], "float32")
            y = paddle.static.data("y", [None, 1], "float32")
            h = x
            for _ in range(depth):
                h = paddle.static.nn.fc(h, hidden, activation="relu")
            pred = paddle.static.nn.fc(h, 1)
            loss = F.mse_loss(pred, y)
            optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main, loss

    def build_lenet(seed):
        paddle.seed(seed)
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 1, 28, 28], "float32")
            y = paddle.static.data("y", [None], "int64")
            loss = F.cross_entropy(LeNet()(x), y)
            optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main, loss

    rng = np.random.RandomState(0)
    xs = rng.standard_normal((batch, hidden)).astype(np.float32)
    ys = rng.standard_normal((batch, 1)).astype(np.float32)

    paddle.enable_static()
    try:
        # fast path: jax feeds pass through, async fetch, donated state;
        # legacy: the preserved pre-change run loop on an identical
        # program.  The two loops are INTERLEAVED over `reps` rounds so
        # machine noise (CPU frequency, co-tenants) hits both equally.
        main, loss = build_mlp(7)
        exe = paddle.static.Executor()
        feed = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
        main2, loss2 = build_mlp(7)
        exe2 = paddle.static.Executor()
        np_feed = {"x": xs, "y": ys}

        for _ in range(3):  # compile + warm both paths
            last = exe.run(main, feed=feed, fetch_list=[loss],
                           return_numpy=False)[0]
            exe2._run_legacy(main2, feed=np_feed, fetch_list=[loss2])
        float(np.asarray(last.data))
        compiles, converts = exe.compile_count, exe.host_feed_converts

        reps, dt_fast, dt_leg = 3, 0.0, 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                last = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)[0]
            float(np.asarray(last.data))  # sync once per round
            dt_fast += time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(steps):
                exe2._run_legacy(main2, feed=np_feed, fetch_list=[loss2])
            dt_leg += time.perf_counter() - t0

        # anomaly-sentry counters (ISSUE 15 gate): the fast loop above
        # ran with the default sentry-less step — time the identical
        # program with FLAGS_anomaly_sentry compiled IN, interleaved
        # round-for-round so machine noise hits both, and report the
        # overhead plus the device-side skipped-step counter (must be
        # 0 on clean data).  This micro is the sentry's WORST case:
        # host+tiny-device work dominates, so the per-grad finiteness
        # scans are visible here while they vanish under real model
        # math — which is exactly why the number is worth recording.
        main3, loss3 = build_mlp(7)
        exe3 = paddle.static.Executor()
        paddle.set_flags({"anomaly_sentry": True})
        try:
            for _ in range(3):
                last3 = exe3.run(main3, feed=feed, fetch_list=[loss3],
                                 return_numpy=False)[0]
            float(np.asarray(last3.data))
        finally:
            paddle.set_flags({"anomaly_sentry": False})
        dt_on = dt_off = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                last = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)[0]
            float(np.asarray(last.data))
            dt_off += time.perf_counter() - t0
            paddle.set_flags({"anomaly_sentry": True})
            try:
                t0 = time.perf_counter()
                for _ in range(steps):
                    last3 = exe3.run(main3, feed=feed,
                                     fetch_list=[loss3],
                                     return_numpy=False)[0]
                float(np.asarray(last3.data))
                dt_on += time.perf_counter() - t0
            finally:
                paddle.set_flags({"anomaly_sentry": False})
        sentry_block = {
            "skipped_steps": exe3.sentry_stats(main3)["skipped_steps"],
            "overhead_pct": round(100.0 * (dt_on / dt_off - 1.0), 2),
            "step_time_ms_on": round(1e3 * dt_on / (reps * steps), 3),
            "step_time_ms_off": round(1e3 * dt_off / (reps * steps), 3),
        }
        steps *= reps

        # conv entry: absolute static-path throughput tracking
        lx = jnp.asarray(rng.standard_normal(
            (lenet_batch, 1, 28, 28)).astype(np.float32))
        ly = jnp.asarray(rng.randint(0, 10, (lenet_batch,),
                                     dtype=np.int64))
        lmain, lloss = build_lenet(9)
        lexe = paddle.static.Executor()
        dt_lenet, lenet_loss = _timed_static_loop(
            lexe, lmain, lloss, {"x": lx, "y": ly}, lenet_steps)
        lenet_compiles = lexe.compile_count

        # static cost model (ISSUE 6): predicted FLOPs/peak-bytes next
        # to the measured numbers, so BENCH_r*.json tracks model
        # accuracy over time (predicted-vs-measured drift per round)
        def _predicted(prog, loss_var, bsz):
            rep = prog.analyze(fetch_list=[loss_var], batch_size=bsz)
            m = rep.memory
            return {
                "fwd_gflops_per_step": round(
                    rep.totals["flops_fwd"] / 1e9, 4),
                "train_gflops_per_step": round(
                    rep.totals["flops_train"] / 1e9, 4),
                "peak_mib_donated": round(
                    m.peak_bytes_donated / 2**20, 2),
                "peak_mib_no_donation": round(
                    m.peak_bytes_no_donation / 2**20, 2),
                "arithmetic_intensity": round(
                    rep.totals["arithmetic_intensity"], 2),
                "unmodeled_ops": rep.totals["unmodeled"]["count"],
                "fusion_candidates": len(rep.fusion_candidates),
            }

        mlp_pred = _predicted(main, loss, batch)
        lenet_pred = _predicted(lmain, lloss, lenet_batch)
        mlp_pred["achieved_gflops_per_sec"] = round(
            mlp_pred["train_gflops_per_step"] * steps / dt_fast, 2)
        lenet_pred["achieved_gflops_per_sec"] = round(
            lenet_pred["train_gflops_per_step"] * lenet_steps / dt_lenet,
            2)
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()

    return {
        "metric": "static_mlp_train_steps_per_sec",
        "value": round(steps / dt_fast, 2),
        "unit": "steps/s",
        "speedup_vs_legacy_executor": round(dt_leg / dt_fast, 3),
        "legacy_steps_per_sec": round(steps / dt_leg, 2),
        "step_time_ms": round(1000 * dt_fast / steps, 3),
        "compile_count": compiles,           # must be 1 (one feed sig)
        "host_feed_converts": converts,      # must be 0 (jax feeds)
        "donated": True,
        "sentry": sentry_block,              # anomaly sentry (ISSUE 15)
        "analyzer": mlp_pred,                # static cost model (ISSUE 6)
        "config": {"hidden": hidden, "depth": depth, "batch": batch,
                   "optimizer": "adam"},
        "static_lenet": {
            "metric": "static_lenet_train_steps_per_sec",
            "value": round(lenet_steps / dt_lenet, 2),
            "unit": "steps/s",
            "step_time_ms": round(1000 * dt_lenet / lenet_steps, 3),
            "compile_count": lenet_compiles,
            "batch": lenet_batch,
            "final_loss": round(lenet_loss, 4),
            "analyzer": lenet_pred,
        },
    }


def bench_serving(args, dev, on_tpu):
    """Serving-engine throughput (ISSUE 4 acceptance): a ragged stream of
    concurrent requests through the dynamic-batching InferenceEngine vs
    the same requests served one-by-one through sequential
    ``Predictor.run``.  Both paths are AOT-warmed (the sequential path
    rides the pad-to-bucket satellite, so neither side recompiles); the
    engine's win is batch coalescing — one XLA dispatch carries many
    requests.  Clients are closed-loop with pipelining depth 8 (each of
    the 8 client threads keeps up to 8 requests in flight, the shape of
    a real RPC frontend).  Sequential and concurrent rounds are
    INTERLEAVED so machine noise hits both equally.  Must show >= 2x at
    concurrency >= 8 on CPU with ``num_compiled_variants()`` flat after
    warmup."""
    import tempfile
    import threading

    import paddle_tpu as paddle
    from paddle_tpu import inference, jit, nn, serving
    from paddle_tpu.jit import InputSpec

    hidden, in_dim, out_dim = 128, 64, 32
    n_requests = args.steps or 240
    concurrency = int(os.environ.get("BENCH_SERVING_CLIENTS", "8"))
    window = int(os.environ.get("BENCH_SERVING_PIPELINE", "8"))
    max_batch = 32
    reps = 3

    paddle.seed(2024)
    model = nn.Sequential(nn.Linear(in_dim, hidden), nn.ReLU(),
                          nn.Linear(hidden, hidden), nn.ReLU(),
                          nn.Linear(hidden, out_dim))
    prefix = os.path.join(tempfile.mkdtemp(prefix="bench_serving_"), "m")
    jit.save(model, prefix,
             input_spec=[InputSpec([None, in_dim], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))

    rng = np.random.RandomState(0)
    reqs = [rng.standard_normal((int(rng.randint(1, 5)), in_dim))
            .astype(np.float32) for _ in range(n_requests)]
    rows_total = sum(r.shape[0] for r in reqs)

    # warm the sequential path across every ragged size (pad-to-bucket
    # compiles the pow2 buckets once) before timing
    for n in sorted({r.shape[0] for r in reqs}):
        np.asarray(pred.run([np.zeros((n, in_dim), np.float32)])[0])
    seq_variants = pred.num_compiled_variants()

    engine = serving.InferenceEngine(pred, max_batch_size=max_batch,
                                     batch_timeout_ms=2.0,
                                     max_queue=4 * n_requests)
    engine.warmup()

    errors = []

    def client(idx):
        try:
            pending = []
            for i in range(idx, n_requests, concurrency):
                pending.append(engine.infer([reqs[i]]))
                while len(pending) >= window:
                    pending.pop(0).result(120)
            for f in pending:
                f.result(120)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{type(e).__name__}: {e}")

    dt_seq = dt_conc = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for r in reqs:
            np.asarray(pred.run([r])[0])    # per-request host sync, as
        dt_seq += time.perf_counter() - t0  # a single-caller server would

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt_conc += time.perf_counter() - t0
    n_requests *= reps
    rows_total *= reps
    stats = engine.stats()
    engine.close()
    if errors:
        raise RuntimeError(f"serving bench clients failed: {errors[:3]}")

    return {
        "metric": "serving_engine_requests_per_sec",
        "value": round(n_requests / dt_conc, 2),
        "unit": "requests/s",
        "speedup_vs_sequential_predictor": round(dt_seq / dt_conc, 3),
        "sequential_requests_per_sec": round(n_requests / dt_seq, 2),
        "rows_per_sec": round(rows_total / dt_conc, 2),
        "concurrency": concurrency,
        "pipeline_depth": window,
        "requests": n_requests,
        "mean_batch_occupancy": round(stats["mean_batch_occupancy"], 3),
        "requests_per_batch": round(stats["requests_per_batch"], 2),
        "padding_waste": round(stats["padding_waste"], 3),
        "latency_ms_p50": round(stats["latency_ms"]["p50"], 2),
        "latency_ms_p95": round(stats["latency_ms"]["p95"], 2),
        "latency_ms_p99": round(stats["latency_ms"]["p99"], 2),
        "compiled_variants_sequential_warm": seq_variants,
        "recompiles_after_warmup": stats["recompiles_after_warmup"],
        "max_batch_size": max_batch,
        "buckets": stats["buckets"],
        "config": {"model": f"mlp {in_dim}-{hidden}-{hidden}-{out_dim}",
                   "ragged_rows": "1-4", "batch_timeout_ms": 2.0},
    }


def bench_generation(args, dev, on_tpu):
    """Ragged-generation serving throughput (ISSUE 7 acceptance): a
    stream of generative requests (ragged prompt lengths AND ragged
    token budgets) through the continuous-batching ``GenerationEngine``
    (paged KV cache, token-level scheduling) vs the same requests
    generated ONE AT A TIME through ``nn.dynamic_decode`` over a dense
    padded KV cache (beam 1, compile-cached via ``cache=True`` so the
    baseline pays zero re-trace — the comparison isolates batching, not
    compile amnesia).  Both sides run the same transformer LM.

    Both sides provision the same serving max context (what the server
    *admits*, not what this stream happens to send): the dense baseline
    pays worst-case provisioning on every token — a [t_max] cache
    update plus dense attention over all t_max rows — while the paged
    engine allocates pages on demand and its context-bucketed decode
    step gathers only the live context.  That asymmetry is the paged
    KV cache's whole point (Ragged Paged Attention, PAPERS.md), on top
    of token-level batching (one compiled step carries ``num_slots``
    sequences, freed slots backfilled mid-flight).  The baseline is
    compile-cached at the single provisioned shape — the standard
    pre-paging deployment (bucketing the *time* dimension per request
    is exactly what the page table replaces).

    Gate: >= 3x token throughput inside the same p99 request-latency
    SLO (``latency_bound_ms``), zero steady-state decode recompiles."""
    import threading

    from paddle_tpu import nn, serving

    n_requests = args.steps or 48
    num_slots = 8
    reps = 2
    max_new_lo, max_new_hi = 16, 48
    prompt_lengths = [4, 6, 8, 12, 16, 24, 32]
    max_context = 512                  # what the server provisions for
    # per-request p99 SLO both paths must meet: a quiet-machine floor,
    # widened on loaded runners by the baseline's own measured tail (a
    # machine-speed proxy) — slot-sharing may not blow up the tail by
    # more than slo_vs_baseline x a dedicated per-request run
    slo_floor_ms = 900.0
    slo_vs_baseline = 3.5
    t_max_cells = max_context          # dense baseline cache rows

    model = serving.PagedDecoderLM(vocab_size=1024, hidden=256,
                                   num_layers=2, num_heads=8,
                                   ffn=2048, seed=7)
    EOS = model.vocab_size - 1
    rng = np.random.RandomState(42)
    prompts = [rng.randint(0, 128, rng.choice(prompt_lengths)).tolist()
               for _ in range(n_requests)]
    budgets = [int(rng.randint(max_new_lo, max_new_hi + 1))
               for _ in range(n_requests)]
    tokens_total = sum(budgets)
    t_decode_max = max_new_hi + 1      # budget tokens + the forced EOS

    # -- baseline: per-request dynamic_decode over a dense padded cache --
    cell = model.make_cell(EOS)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=EOS,
                               beam_size=1)

    def gen_one(prompt, limit):
        st = model.init_cell_state(prompt, t_max_cells)
        st["limit"] = np.full((1,), limit, np.int32)
        dec.start_token = int(prompt[-1])
        seq, _, lens = nn.dynamic_decode(dec, st,
                                         max_step_num=t_decode_max,
                                         return_length=True, cache=True)
        n = int(np.asarray(lens.numpy())[0, 0])
        return np.asarray(seq.numpy())[0, 0, :n]

    # warm both paths: every prompt-length shape for the baseline's
    # eager prefill, the cached decode loop, and the engine's buckets
    for L in sorted({len(p) for p in prompts}):
        gen_one(list(range(1, L + 1)), 2)
    # pool sized for what the slots can actually reserve (page demand
    # follows the traffic, not the advertised context — the paged
    # cache's memory win); prompt buckets cover the traffic mix
    engine = serving.GenerationEngine(model, num_slots=num_slots,
                                      page_size=8,
                                      max_context=max_context,
                                      num_pages=128,
                                      prompt_buckets=[8, 16, 32],
                                      max_queue=4 * n_requests)
    engine.warmup()

    errors = []
    conc_lat: list = []

    def client(idx):
        try:
            for i in range(idx, n_requests, num_slots):
                t0 = time.perf_counter()
                out = engine.generate_sync(prompts[i], timeout=300,
                                           max_new_tokens=budgets[i])
                conc_lat.append(time.perf_counter() - t0)
                if len(out) != budgets[i]:
                    errors.append(f"req {i}: {len(out)} tokens, "
                                  f"budget {budgets[i]}")
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{type(e).__name__}: {e}")

    dt_seq = dt_conc = 0.0
    seq_lat: list = []
    for _ in range(reps):
        # sequential per-request generation, as a single-caller server
        t0 = time.perf_counter()
        for p, b in zip(prompts, budgets):
            t1 = time.perf_counter()
            out = gen_one(p, b)
            seq_lat.append(time.perf_counter() - t1)
            if len(out) != b + 1 or out[-1] != EOS:
                errors.append(f"baseline: {len(out)} tokens for "
                              f"budget {b}")
        dt_seq += time.perf_counter() - t0

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(num_slots)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt_conc += time.perf_counter() - t0
    stats = engine.stats()
    engine.close()
    if errors:
        raise RuntimeError(f"generation bench failed: {errors[:3]}")

    def p99(lat):
        return float(np.percentile(np.asarray(lat) * 1000.0, 99))

    toks = tokens_total * reps
    bound_ms = max(slo_floor_ms, slo_vs_baseline * p99(seq_lat))
    return {
        "metric": "serving_generation_tokens_per_sec",
        "value": round(toks / dt_conc, 2),
        "unit": "tokens/s",
        "speedup_vs_dynamic_decode": round(dt_seq / dt_conc, 3),
        "dynamic_decode_tokens_per_sec": round(toks / dt_seq, 2),
        "requests": n_requests * reps,
        "num_slots": num_slots,
        "latency_bound_ms": round(bound_ms, 2),
        "p99_latency_ms": round(p99(conc_lat), 2),
        "p99_latency_ms_baseline": round(p99(seq_lat), 2),
        "p99_within_bound": p99(conc_lat) <= bound_ms,
        "ttft_ms_p95": round(stats["ttft_ms"]["p95"], 2),
        "mean_slot_occupancy": round(stats["mean_slot_occupancy"], 3),
        "prefill_decode_ratio": round(stats["prefill_decode_ratio"], 3),
        "decode_steps": stats["counters"]["decode_steps"],
        "recompiles_after_warmup": stats["recompiles_after_warmup"],
        "page_pool_pages": stats["page_pool"]["num_pages"],
        "ctx_buckets": stats["ctx_buckets"],
        "config": {"model": "paged-lm 256h x2L 8H ffn2048", "vocab": 1024,
                   "prompt_lengths": prompt_lengths,
                   "max_new": [max_new_lo, max_new_hi],
                   "page_size": 8, "max_context": max_context},
    }


def bench_pallas(args, dev, on_tpu):
    """Pallas kernel tier (ISSUE 11): BERT and GPT *static* training
    suites timed with the tier ON vs OFF, interleaved on the SAME
    program/Executor — the tier state rides the compile cache key, so
    each flag flip dispatches its own cached executable and the donated
    state threads through both.  Reports step time + MFU per tier and
    the realized kernel list off the compile records, plus the serving
    decode suite with the paged-attention Pallas kernel registered vs
    the gather reference.  On CPU the kernels run in interpret mode
    (FLAGS_pallas_interpret) — the absolute numbers are meaningless
    there, the JSON *shape* and the realized-kernel evidence are what
    BENCH_* tracks; the speedups become real on TPU."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.flags import get_flag, set_flags
    from paddle_tpu.observability import explain_compiles

    if on_tpu:
        bert_cfg = dict(vocab=30522, hidden=768, layers=12, heads=12,
                        ffn=3072, seq=512, batch=16)
        gpt_cfg = dict(vocab=50257, hidden=1024, layers=8, heads=16,
                       ffn=4096, seq=1024, batch=8)
        steps, reps = (args.steps or 10), 2
    else:
        bert_cfg = dict(vocab=1000, hidden=128, layers=2, heads=4,
                        ffn=512, seq=128, batch=8)
        gpt_cfg = dict(vocab=1000, hidden=128, layers=2, heads=4,
                       ffn=512, seq=128, batch=4)
        steps, reps = (args.steps or 2), 2

    peak = _peak_flops(dev)
    prev_interpret = get_flag("pallas_interpret")
    prev_kernels = get_flag("use_pallas_kernels")
    paddle.enable_static()
    try:
        if not on_tpu:
            set_flags({"pallas_interpret": True})

        def run_suite(build, cfg):
            main, loss, feeds_fn = build(**cfg)
            exe = paddle.static.Executor()
            feed = feeds_fn(np.random.RandomState(0))
            tokens = cfg["batch"] * cfg["seq"]

            def loop(n):
                last = None
                for _ in range(n):
                    last = exe.run(main, feed=feed, fetch_list=[loss],
                                   return_numpy=False)[0]
                return float(np.asarray(last.data))

            # warm BOTH tier variants (each is its own cache entry)
            set_flags({"use_pallas_kernels": True})
            loop(2)
            set_flags({"use_pallas_kernels": False})
            loop(2)
            warm_compiles = exe.compile_count

            dt_on = dt_off = 0.0
            for _ in range(reps):
                set_flags({"use_pallas_kernels": True})
                t0 = time.perf_counter()
                loss_on = loop(steps)
                dt_on += time.perf_counter() - t0
                set_flags({"use_pallas_kernels": False})
                t0 = time.perf_counter()
                loss_off = loop(steps)
                dt_off += time.perf_counter() - t0
            n = steps * reps
            # analyze under the tier-ON flag state: the realized
            # marking is flag-gated exactly like the executor pass
            set_flags({"use_pallas_kernels": True})
            rep = main.analyze(fetch_list=[loss], top_k=None)
            flops = rep.totals["flops_train"]
            sps_on, sps_off = n / dt_on, n / dt_off
            recs = [r for r in explain_compiles("executor")["records"]
                    if r["identity"] == main._serial
                    and r.get("kernels")]
            kernels = recs[-1]["kernels"] if recs else []
            realized = [c["realized"] for c in rep.fusion_candidates
                        if c.get("realized")]
            out = {
                "step_time_ms_pallas_on": round(1000 * dt_on / n, 3),
                "step_time_ms_pallas_off": round(1000 * dt_off / n, 3),
                "speedup_pallas_on_vs_off": round(dt_off / dt_on, 3),
                "tokens_per_sec_on": round(tokens * sps_on, 2),
                "tokens_per_sec_off": round(tokens * sps_off, 2),
                "mfu_on": round(flops * sps_on / peak, 4) if peak else 0.0,
                "mfu_off": round(flops * sps_off / peak, 4) if peak
                else 0.0,
                "mfu_delta": round(flops * (sps_on - sps_off) / peak, 4)
                if peak else 0.0,
                "final_loss_on": round(loss_on, 4),
                "final_loss_off": round(loss_off, 4),
                "realized_kernels": kernels,
                "fusion_candidates_realized":
                    f"{len(realized)}/{len(rep.fusion_candidates)}",
                "compile_count": exe.compile_count,
                "recompiles_after_warmup":
                    exe.compile_count - warm_compiles,
                "config": dict(cfg),
            }
            exe.close()
            return out

        bert = run_suite(build_bert_static, bert_cfg)
        gpt = run_suite(build_gpt_static, gpt_cfg)
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
        set_flags({"pallas_interpret": prev_interpret,
                   "use_pallas_kernels": prev_kernels})

    decode = _bench_paged_decode(on_tpu)

    return {
        "metric": "pallas_tier_bert_static_speedup_on_vs_off",
        "value": bert["speedup_pallas_on_vs_off"],
        "unit": "x",
        "interpret_mode": not on_tpu,
        "bert_static": bert,
        "gpt_static": gpt,
        "generation_decode": decode,
    }


def _bench_paged_decode(on_tpu):
    """Decode tokens/s with the Pallas paged-attention kernel
    registered vs the gather reference (same ragged request mix, dyadic
    model => token parity is bitwise-checkable)."""
    from paddle_tpu import serving
    from paddle_tpu.core.flags import get_flag, set_flags
    from paddle_tpu.ops import attention as _attn

    n_requests, budget = (16, 24) if on_tpu else (6, 8)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 64, rng.choice([3, 5, 9])).tolist()
               for _ in range(n_requests)]
    prev_interpret = get_flag("pallas_interpret")
    prev_kernels = get_flag("use_pallas_kernels")

    def run(tier_on):
        set_flags({"use_pallas_kernels": tier_on,
                   "pallas_interpret": tier_on and not on_tpu})
        _attn.register_paged_attention_kernel(None)
        # head_dim = 256/2 = 128: the gate's 128-lane alignment
        model = serving.PagedDecoderLM(vocab_size=128, hidden=256,
                                       num_layers=2, num_heads=2,
                                       seed=7, dyadic=True)
        engine = serving.GenerationEngine(model, num_slots=4,
                                          page_size=8, max_context=64,
                                          num_pages=64)
        engine.warmup()
        t0 = time.perf_counter()
        outs = [engine.generate_sync(p, max_new_tokens=budget,
                                     timeout=600) for p in prompts]
        dt = time.perf_counter() - t0
        stats = engine.stats()
        engine.close()
        _attn.register_paged_attention_kernel(None)
        return outs, dt, stats

    try:
        ref_outs, dt_ref, _ = run(False)
        pal_outs, dt_pal, stats = run(True)
    finally:
        _attn.register_paged_attention_kernel(None)
        set_flags({"pallas_interpret": prev_interpret,
                   "use_pallas_kernels": prev_kernels})
    toks = n_requests * budget
    from paddle_tpu.ops.pallas.support import kernel_selections
    return {
        "tokens_per_sec_paged_kernel": round(toks / dt_pal, 2),
        "tokens_per_sec_reference": round(toks / dt_ref, 2),
        "token_parity": ref_outs == pal_outs,
        "kernel_selected": kernel_selections.get("paged_attention", 0) > 0,
        "recompiles_after_warmup": stats["recompiles_after_warmup"],
        "requests": n_requests,
        "budget_tokens": budget,
    }


def bench_lenet_dygraph(args):
    """Dygraph (eager, un-jitted) smoke benchmark (BASELINE.json
    configs[0]): LeNet/MNIST shapes on CPU, measuring per-op Python
    dispatch + tape overhead.  Runs in a subprocess so the CPU backend
    doesn't fight the TPU client in this process."""
    code = (
        "import sys, time, json; sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')  # env var alone is "
        "read too late when a sitecustomize pre-imports jax\n"
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.nn.functional as F\n"
        "from paddle_tpu import optimizer\n"
        "from paddle_tpu.vision.models import LeNet\n"
        "paddle.seed(0)\n"
        "model = LeNet()\n"
        "opt = optimizer.Adam(learning_rate=1e-3,"
        " parameters=model.parameters())\n"
        "x = paddle.to_tensor(np.random.randn(64, 1, 28, 28)"
        ".astype('float32'))\n"
        "y = paddle.to_tensor(np.random.randint(0, 10, (64,))"
        ".astype('int64'))\n"
        "def one_step():\n"
        "    loss = F.cross_entropy(model(x), y)\n"
        "    loss.backward(); opt.step(); opt.clear_grad()\n"
        "    return float(loss)\n"
        "for _ in range(3): one_step()\n"
        "t0 = time.perf_counter(); n = 30\n"
        "for _ in range(n): last = one_step()\n"
        "dt = time.perf_counter() - t0\n"
        "from paddle_tpu import profiler as _prof\n"
        "p = _prof.Profiler(timer_only=True); p.start()\n"
        "for _ in range(5): one_step()  # separate profiled pass\n"
        "p.stop()\n"
        "top_ops = [[nm, c, round(ms, 2)]"
        " for nm, c, ms in p.key_averages()[:5]]\n"
        "import tempfile, os as _os\n"
        "from paddle_tpu import inference, jit\n"
        "from paddle_tpu.jit import InputSpec\n"
        "pfx = _os.path.join(tempfile.mkdtemp(), 'm')\n"
        "jit.save(model, pfx, input_spec=[InputSpec([None,1,28,28],"
        " 'float32')])\n"
        "pred = inference.create_predictor(inference.Config(pfx))\n"
        "xi = np.zeros((1, 1, 28, 28), 'float32')\n"
        "pred.run([xi])\n"
        "t0 = time.perf_counter()\n"
        "for _ in range(50): outs = pred.run([xi])\n"
        "float(np.asarray(outs[0]).sum())\n"
        "infer_ms = (time.perf_counter() - t0) / 50 * 1000\n"
        "print(json.dumps({'step_time_ms': round(1000 * dt / n, 3),"
        " 'steps_per_sec': round(n / dt, 2), 'final_loss': round(last, 4),"
        " 'predictor_latency_ms_bs1': round(infer_ms, 3),"
        " 'predictor_recompiles': pred.num_compiled_variants(),"
        " 'top_host_ops_ms': top_ops}))\n"
        % os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=600)
        line = out.stdout.strip().splitlines()[-1]
        res = json.loads(line)
    except Exception as e:  # pragma: no cover - defensive
        return {"metric": "lenet_mnist_dygraph_step_time_ms",
                "error": f"{type(e).__name__}: {e}"}
    res.update({"metric": "lenet_mnist_dygraph_step_time_ms",
                "unit": "ms/step", "batch": 64, "platform": "cpu",
                "mode": "eager"})
    return res


def bench_multichip(args):
    """Multichip GPT-tiny collective-efficiency + overlap run (ISSUE
    10/14/17 gates): tools/comm_smoke.py on 8 virtual CPU devices in a
    subprocess (this process's jax is already initialised with its own
    device count), comparing int8 block-scaled grad_comm against the
    fp32 wire baseline — wire bytes/step (measured == cost-model
    prediction), loss-trajectory parity under error feedback,
    recompiles — and overlap=auto against overlap=none: step time vs
    the max(compute, comm) bound, with the perf observatory's
    exposed-vs-hidden comm split embedded next to the wire-byte ratio
    (result key ``overlap_gate``).  ISSUE 17 adds the hybrid rows: a
    {dp:4, mp:2} tensor-parallel run with per-axis wire accounting
    (``hybrid`` key: dp/mp bytes each measured == predicted, plus the
    forward param-gather ledger) and a ZeRO-3 run with params sharded
    at rest (``zero3`` key: rscatter buckets + per-shard peak bytes
    vs the replicated baseline)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "comm_smoke.py"), "--json"]
    if args.steps:
        cmd += ["--steps", str(args.steps)]
    try:
        out = subprocess.run(cmd, env=env, capture_output=True,
                             text=True, timeout=600)
        line = out.stdout.strip().splitlines()[-1]
        res = json.loads(line)
        if out.returncode != 0:
            res["gate_failures"] = out.stderr.strip().splitlines()[-5:]
    except Exception as e:  # pragma: no cover - defensive
        return {"metric": "multichip_gpt_int8_wire_ratio_vs_fp32",
                "error": f"{type(e).__name__}: {e}"}
    res.update({"platform": "cpu", "devices": 8,
                "meshes": [{"dp": 8}, {"dp": 4, "mp": 2}]})
    hyb = res.get("hybrid_dp4_mp2") or {}
    z3 = res.get("zero3") or {}
    int8 = res.get("int8") or {}
    res["hybrid"] = {
        "mesh": {"dp": 4, "mp": 2},
        "axis_wire_bytes_per_step": hyb.get("axis_wire_bytes_per_step"),
        "predicted_axis_wire_bytes":
            hyb.get("predicted_axis_wire_bytes"),
        "gather_wire_bytes_per_step":
            hyb.get("gather_wire_bytes_per_step"),
        "gather_collectives_per_step":
            hyb.get("gather_collectives_per_step"),
        "step_ms_min": hyb.get("step_ms_min"),
        "compiles": hyb.get("compiles"),
    }
    res["zero3_summary"] = {
        "algorithms": z3.get("algorithms"),
        "peak_bytes_per_shard": z3.get("peak_bytes_per_shard"),
        "replicated_peak_bytes_per_shard":
            int8.get("peak_bytes_per_shard"),
        "wire_bytes_per_step": z3.get("wire_bytes_per_step"),
        "step_ms_min": z3.get("step_ms_min"),
        "compiles": z3.get("compiles"),
    }
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--profile", type=str, default=None,
                    help="directory for a jax profiler trace of timed steps")
    ap.add_argument("--small", action="store_true",
                    help="force the tiny CPU config")
    ap.add_argument("--suite", type=str, default="all",
                    choices=["all", "bert", "gpt", "resnet", "lenet",
                             "static", "serving", "multichip", "pallas"],
                    help="which benchmarks to run (default: all)")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" and not args.small

    extra = {}
    if args.suite in ("all", "resnet"):
        try:
            extra["resnet50"] = _retry_bench(bench_resnet50, args, dev,
                                             on_tpu)
        except Exception as e:
            extra["resnet50"] = {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "error": f"{type(e).__name__}: {e}"}
    if args.suite in ("all", "gpt"):
        try:
            extra["gpt"] = _retry_bench(bench_gpt, args, dev, on_tpu)
        except Exception as e:
            extra["gpt"] = {
                "metric": "gpt_pretrain_tokens_per_sec_per_chip",
                "error": f"{type(e).__name__}: {e}"}
    if args.suite in ("all", "static"):
        try:
            extra["static"] = _retry_bench(bench_static, args, dev, on_tpu)
        except Exception as e:
            extra["static"] = {
                "metric": "static_mlp_train_steps_per_sec",
                "error": f"{type(e).__name__}: {e}"}
    if args.suite in ("all", "serving"):
        try:
            extra["serving"] = _retry_bench(bench_serving, args, dev,
                                            on_tpu)
        except Exception as e:
            extra["serving"] = {
                "metric": "serving_engine_requests_per_sec",
                "error": f"{type(e).__name__}: {e}"}
        try:
            extra["serving_generation"] = _retry_bench(
                bench_generation, args, dev, on_tpu)
        except Exception as e:
            extra["serving_generation"] = {
                "metric": "serving_generation_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"}
    if args.suite in ("all", "pallas"):
        try:
            extra["pallas"] = _retry_bench(bench_pallas, args, dev,
                                           on_tpu)
        except Exception as e:
            extra["pallas"] = {
                "metric": "pallas_tier_bert_static_speedup_on_vs_off",
                "error": f"{type(e).__name__}: {e}"}
    if args.suite in ("all", "multichip"):
        extra["multichip"] = bench_multichip(args)
    if args.suite in ("all", "lenet"):
        extra["lenet_dygraph"] = bench_lenet_dygraph(args)

    result = None
    if args.suite in ("all", "bert"):
        try:
            result = _retry_bench(bench_bert, args, dev, on_tpu)
        except Exception as e:
            extra["bert_error"] = {"error": f"{type(e).__name__}: {e}"}
    if result is None:
        # never exit non-zero without a JSON line: promote the first
        # successful secondary result (round-4 lesson — rc=1 loses the
        # round's perf evidence entirely)
        for k in ("gpt", "resnet50", "static", "serving", "pallas",
                  "multichip", "lenet_dygraph"):
            if k in extra and "error" not in extra[k]:
                result = extra.pop(k)
                break
    if result is None:
        result = {"metric": "bench_failed", "value": 0.0, "unit": "none",
                  "vs_baseline": 0.0}

    result.setdefault("device", getattr(dev, "device_kind", dev.platform))
    result.setdefault("platform", dev.platform)
    if extra:
        result["extra"] = extra
    print(json.dumps(result))


if __name__ == "__main__":
    main()
