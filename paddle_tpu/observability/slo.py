"""Declarative SLO monitors with rolling windows and burn-rate alerts.

An :class:`SLORule` names a monitor metric, an objective and a rolling
window; the :class:`SLOMonitor` evaluates every rule against windowed
*deltas* of the process-wide ``utils.monitor`` registry — the same
counters and histograms the serving engines, the Executor step anatomy
(``step.host_ms`` / ``step.device_ms``) and the fault layer already
feed — so declaring an SLO never adds a hot-path instrument.

Three rule shapes.  ``per=`` is explicit and wins; the rest is
resolved from what the metric is (``quantile=`` and ``per=`` are
mutually exclusive — a rule can't be both):

- **ratio** (``per=`` names a denominator counter): windowed
  ``Δmetric / Δper`` vs an ``objective`` fraction — shed rate,
  deadline-expiry rate.  A histogram-observed numerator counts its
  windowed *observations*.
- **quantile** (no ``per``, the metric has a histogram): the windowed
  ``quantile`` (default p99) must stay at/below ``objective`` —
  serving p99 latency, decode TTFT, training step time.
- **rate** (plain counter, no ``per``): windowed ``Δmetric /
  Δseconds`` vs an ``objective`` per-second rate.

``burn = measured / objective`` is the burn rate: 1.0 means consuming
the objective exactly; the rule breaches when ``burn >= burn_rate``
(so ``burn_rate=2`` alerts only on *fast* burns, the classic
multi-window page rule's fast arm).  Windows hold no samples of their
own: the monitor keeps timestamped snapshots of the registry and
subtracts, so an idle window (no traffic) is "no data" — healthy, not
breached.

Transitions emit ``slo`` tracer events (breach / recover) through the
one-None-check hook, set ``slo.<rule>.*`` monitor gauges (exported as
``paddle_tpu_slo_*`` by ``prometheus_text``), and drive ``/healthz``:
with a monitor installed, any breached rule degrades the endpoint to
503 with the breach reasons in the body.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

from ..core import obs_hook
from ..utils import monitor

__all__ = ["SLORule", "SLOMonitor", "install_slo_monitor",
           "uninstall_slo_monitor", "get_slo_monitor", "slo_status",
           "standard_serving_rules"]


class SLORule:
    """One service-level objective over a monitor metric.

    Args:
        metric: monitor stat/histogram name (``serving.latency_ms``,
            ``serving.shed``, ``step.device_ms``, ...).
        objective: the target — milliseconds for quantile rules, a
            fraction for ratio rules, events/second for rate rules.
        window: rolling evaluation window, seconds.
        burn_rate: breach when ``measured / objective >= burn_rate``.
        name: report/gauge label (defaults to a metric-derived slug).
        quantile: which windowed quantile a histogram metric is held
            to (default 0.99).
        per: denominator counter for ratio rules.
        min_count: a quantile rule judges only windows holding at
            least this many observations (default 1) — raise it so a
            freshly-installed monitor can't degrade ``/healthz`` off a
            handful of samples before the window has filled.
    """

    def __init__(self, metric: str, objective: float,
                 window: float = 60.0, burn_rate: float = 1.0,
                 name: Optional[str] = None,
                 quantile: Optional[float] = None,
                 per: Optional[str] = None,
                 min_count: int = 1):
        if objective <= 0:
            raise ValueError("objective must be > 0")
        if window <= 0:
            raise ValueError("window must be > 0")
        if burn_rate <= 0:
            raise ValueError("burn_rate must be > 0")
        if quantile is not None and not (0.0 < quantile < 1.0):
            raise ValueError("quantile must lie in (0, 1)")
        if quantile is not None and per is not None:
            raise ValueError(
                "quantile= and per= are mutually exclusive: a rule is "
                "either a windowed quantile of the metric or a ratio "
                "over a denominator, not both")
        if min_count < 1:
            raise ValueError("min_count must be >= 1")
        self.min_count = int(min_count)
        self.metric = str(metric)
        self.objective = float(objective)
        self.window = float(window)
        self.burn_rate = float(burn_rate)
        self.quantile = quantile
        self.per = per
        self.name = name or self.metric.replace(".", "_")

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "objective": self.objective, "window": self.window,
                "burn_rate": self.burn_rate, "quantile": self.quantile,
                "per": self.per, "min_count": self.min_count}

    def __repr__(self):
        return (f"SLORule({self.metric!r}, objective={self.objective}, "
                f"window={self.window}, burn_rate={self.burn_rate})")


# gauge value for a non-finite measurement: finite so JSON exports of
# the registry stay strict-parseable, large enough that any dashboard
# threshold alert still fires during the "unambiguously burning"
# zero-denominator condition
_INF_GAUGE = 1e12


def _json_num(v):
    """A measurement as it may be serialized: non-finite floats become
    the JSON-safe string ``"inf"``/``"-inf"`` (the bare token
    ``Infinity`` json.dumps would emit breaks strict parsers — jq,
    JSON.parse, the chrome trace viewer)."""
    if v is None or isinstance(v, str) or math.isfinite(v):
        return v
    return "inf" if v > 0 else "-inf"


def standard_serving_rules(p99_latency_ms: Optional[float] = None,
                           ttft_p95_ms: Optional[float] = None,
                           shed_ratio: Optional[float] = None,
                           expiry_ratio: Optional[float] = None,
                           step_p95_ms: Optional[float] = None,
                           window: float = 60.0) -> List[SLORule]:
    """The four SLOs the ISSUE names, as one declarative bundle: pass
    only the objectives you serve (None skips the rule)."""
    rules: List[SLORule] = []
    if p99_latency_ms is not None:
        rules.append(SLORule("serving.latency_ms", p99_latency_ms,
                             window=window, quantile=0.99,
                             name="serving_p99_latency_ms"))
    if ttft_p95_ms is not None:
        rules.append(SLORule("serving.decode.ttft_ms", ttft_p95_ms,
                             window=window, quantile=0.95,
                             name="decode_p95_ttft_ms"))
    if shed_ratio is not None:
        rules.append(SLORule("serving.shed", shed_ratio, window=window,
                             per="serving.requests",
                             name="serving_shed_ratio"))
    if expiry_ratio is not None:
        rules.append(SLORule("serving.deadline_expired", expiry_ratio,
                             window=window, per="serving.requests",
                             name="serving_expiry_ratio"))
    if step_p95_ms is not None:
        rules.append(SLORule("step.device_ms", step_p95_ms,
                             window=window, quantile=0.95,
                             name="train_p95_step_ms"))
    return rules


class SLOMonitor:
    """Evaluates a rule set against rolling windows of the monitor
    registry.  :meth:`poll` snapshots, evaluates, updates gauges and
    emits transition events; it is cheap enough to run per ``/healthz``
    probe (that is exactly how the HTTP layer drives it)."""

    def __init__(self, rules):
        rules = list(rules)
        if not rules:
            raise ValueError("an SLOMonitor needs at least one rule")
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.rules: List[SLORule] = rules
        self._max_window = max(r.window for r in rules)
        self._metrics = sorted({m for r in rules
                                for m in (r.metric, r.per) if m})
        # reentrant: the flight recorder reads status() from the
        # SIGTERM handler, which can interrupt the SAME thread inside
        # either lock — a plain Lock would self-deadlock the crash
        # dump at exactly the preemption it exists to record
        self._lock = threading.RLock()
        # serializes whole poll() evaluations: concurrent /healthz
        # probes (ThreadingHTTPServer = one thread per connection)
        # must not interleave snapshot-append, transition detection
        # and gauge/event emission, or a breach double-fires and a
        # slow thread overwrites _last with stale status
        self._poll_lock = threading.RLock()
        self._snaps: List[tuple] = []       # (ts, {metric: entry})
        self._breached: Dict[str, bool] = {r.name: False for r in rules}
        self._last: Optional[dict] = None

    # -- snapshots ---------------------------------------------------------
    def _snapshot(self) -> dict:
        # targeted reads, not all_stats(): poll() runs per /healthz
        # probe and the registry can hold hundreds of entries (per-
        # device memory gauges, per-engine mirrors) — copying it all
        # to read the rules' few metrics is pure lock contention
        return {m: {"h": monitor.histogram_raw(m),
                    "v": monitor.get_stat(m)}
                for m in self._metrics}

    @staticmethod
    def _window_delta(cur_e: dict, base_e: Optional[dict]) -> dict:
        """cur - base for one metric entry (base None = everything)."""
        out = {"v": cur_e["v"] - (base_e["v"] if base_e else 0)}
        ch = cur_e.get("h")
        if ch is not None:
            bh = (base_e or {}).get("h")
            if bh is None:
                out["counts"] = list(ch["counts"])
                out["n"] = ch["count"]
            else:
                out["counts"] = [a - b for a, b in
                                 zip(ch["counts"], bh["counts"])]
                out["n"] = ch["count"] - bh["count"]
        return out

    # -- evaluation --------------------------------------------------------
    def _evaluate(self, rule: SLORule, cur: dict, base: Optional[dict],
                  dt: float) -> dict:
        # no base snapshot yet (first poll after install): evaluating
        # the process's whole cumulative history as "the window" would
        # alert on traffic that predates the objective — report no
        # data instead
        d = (self._window_delta(cur[rule.metric], base.get(rule.metric))
             if base is not None else {})
        measured: Optional[float] = None
        kind = "rate"
        if rule.per:        # counter ratio — explicit per= wins, even
            kind = "ratio"  # when the numerator metric has a histogram
            if base is not None:
                # a histogram-observed metric counts its windowed
                # observations, a plain counter its delta — on BOTH
                # sides: a histogram denominator's stat value is
                # always 0, which would make any numerator event an
                # inf burn and permanently degrade /healthz
                pe = cur[rule.per]
                pd = self._window_delta(pe, base.get(rule.per))
                dp = pd["n"] if pe.get("h") is not None else pd["v"]
                dv = (d["n"] if cur[rule.metric].get("h") is not None
                      else d["v"])
                if dp > 0:
                    measured = dv / dp
                elif dv > 0:    # events with zero denominator traffic:
                    measured = math.inf     # unambiguously burning
        elif cur[rule.metric].get("h") is not None:   # windowed quantile
            kind = "quantile"
            if d.get("n", 0) >= rule.min_count:
                ch = cur[rule.metric]["h"]
                # lifetime min/max bound any window's values: without
                # them a windowed p99 can overshoot the true extreme
                # by a bucket width and falsely breach the objective
                measured = monitor.quantile_from_counts(
                    d["counts"], d["n"], rule.quantile or 0.99,
                    vmin=ch["min"], vmax=ch["max"])
        else:                               # counter rate per second
            if base is not None and dt > 0 and d["v"] != 0:
                measured = d["v"] / dt
        burn = 0.0 if measured is None else measured / rule.objective
        return {"name": rule.name, "metric": rule.metric, "kind": kind,
                "objective": rule.objective, "window": rule.window,
                "burn_rate": rule.burn_rate,
                "quantile": rule.quantile, "per": rule.per,
                "measured": (None if measured is None
                             else float(measured)),
                "burn": float(burn),
                "breached": measured is not None
                and burn >= rule.burn_rate}

    def poll(self, now: Optional[float] = None) -> dict:
        """Snapshot the registry, evaluate every rule over its window,
        update gauges and transition events; returns the status dict.
        ``now`` (monotonic seconds) is injectable for deterministic
        window tests."""
        with self._poll_lock:
            return self._poll_locked(now)

    def _poll_locked(self, now: Optional[float]) -> dict:
        now = time.monotonic() if now is None else float(now)
        cur = self._snapshot()
        with self._lock:
            self._snaps.append((now, cur))
            # retain one snapshot older than the longest window so a
            # full-window base survives pruning
            cutoff = now - self._max_window
            while len(self._snaps) > 2 and self._snaps[1][0] <= cutoff:
                self._snaps.pop(0)
            snaps = list(self._snaps)
            prev_breached = dict(self._breached)
        results = []
        for rule in self.rules:
            base_ts, base = None, None
            target = now - rule.window
            for ts, snap in snaps[:-1]:
                if ts <= target or base_ts is None:
                    base_ts, base = ts, snap
                if ts > target:
                    break
            res = self._evaluate(rule, cur, base,
                                 now - base_ts if base_ts is not None
                                 else 0.0)
            res["window_actual"] = (now - base_ts
                                    if base_ts is not None else 0.0)
            results.append(res)
        # gauges + transitions (outside the lock: monitor locks itself)
        trc = obs_hook._tracer
        reasons = []
        for res in results:
            nm = res["name"]
            b = res["burn"]
            monitor.stat_set(f"slo.{nm}.burn",
                             round(b, 6) if math.isfinite(b)
                             else _INF_GAUGE)
            monitor.stat_set(f"slo.{nm}.breached", int(res["breached"]))
            m = res["measured"]
            if m is not None:
                monitor.stat_set(f"slo.{nm}.measured",
                                 round(m, 6) if math.isfinite(m)
                                 else _INF_GAUGE)
            else:
                # no data this window: drop the gauge rather than
                # freeze it at the last (possibly breach-level) value
                monitor.stat_reset(f"slo.{nm}.measured")
            was = prev_breached.get(nm, False)
            if res["breached"] and not was:
                monitor.stat_add("slo.breaches")
                if trc is not None:
                    # event args land verbatim in flight dumps and
                    # chrome-trace exports: keep them strict-JSON-safe
                    trc.emit("slo", "breach", args=dict(
                        rule=nm, metric=res["metric"],
                        measured=_json_num(res["measured"]),
                        objective=res["objective"],
                        burn=_json_num(res["burn"])))
            elif was and not res["breached"]:
                if trc is not None:
                    trc.emit("slo", "recover", args=dict(
                        rule=nm, measured=_json_num(res["measured"]),
                        objective=res["objective"]))
            if res["breached"]:
                m = res["measured"]
                reasons.append(
                    f"{nm}: measured "
                    f"{'inf' if not math.isfinite(m) else round(m, 3)} "
                    f"vs objective {res['objective']} over "
                    f"{res['window']}s (burn {res['burn']:.2f}x)")
        degraded = bool(reasons)
        monitor.stat_set("slo.degraded", int(degraded))
        # the status dict is serialized verbatim (/perf responses,
        # dump_metrics JSONL, flight dumps): a zero-denominator ratio's
        # math.inf would render as the non-standard token Infinity and
        # break strict JSON consumers — carry it as the string "inf"
        for res in results:
            for k in ("measured", "burn"):
                res[k] = _json_num(res[k])
        status = {
            "installed": True,
            "status": "degraded" if degraded else "ok",
            "time": time.time(),
            "rules": results,
            "breached": [r["name"] for r in results if r["breached"]],
            "reasons": reasons,
        }
        with self._lock:
            for res in results:
                self._breached[res["name"]] = res["breached"]
            self._last = status
        return status

    def status(self) -> Optional[dict]:
        """The most recent :meth:`poll` result (None before the first)."""
        with self._lock:
            return self._last


_lock = threading.Lock()
_monitor: Optional[SLOMonitor] = None


def _clear_rule_gauges(m: Optional[SLOMonitor]) -> None:
    """Remove an outgoing monitor's per-rule gauges from the registry:
    a dashboard must not keep seeing ``slo.<rule>.breached 1`` from a
    monitor that no longer exists."""
    if m is None:
        return
    for rule in m.rules:
        for suffix in ("burn", "breached", "measured"):
            monitor.stat_reset(f"slo.{rule.name}.{suffix}")


def install_slo_monitor(rules) -> SLOMonitor:
    """Install a process-wide monitor over ``rules`` (a list of
    :class:`SLORule`, or anything :class:`SLOMonitor` accepts);
    replaces any previous one (whose per-rule gauges are cleared).
    Returns the monitor."""
    global _monitor
    m = rules if isinstance(rules, SLOMonitor) else SLOMonitor(rules)
    with _lock:
        prev, _monitor = _monitor, m
    if prev is not m:
        _clear_rule_gauges(prev)
    monitor.stat_set("slo.degraded", 0)
    return m


def uninstall_slo_monitor() -> None:
    global _monitor
    with _lock:
        prev, _monitor = _monitor, None
    _clear_rule_gauges(prev)
    monitor.stat_set("slo.degraded", 0)


def get_slo_monitor() -> Optional[SLOMonitor]:
    return _monitor


def slo_status(poll: bool = True) -> dict:
    """Current SLO state.  With no monitor installed: ``{"installed":
    False, "status": "ok"}`` — absence of objectives is healthy, not
    unknown.  ``poll=False`` returns the last evaluation without
    re-snapshotting (what flight dumps embed)."""
    m = _monitor
    if m is None:
        return {"installed": False, "status": "ok", "rules": [],
                "breached": [], "reasons": []}
    if poll:
        return m.poll()
    st = m.status()
    return st if st is not None else {
        "installed": True, "status": "ok", "rules": [], "breached": [],
        "reasons": []}
