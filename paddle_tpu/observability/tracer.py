"""Structured event tracer: a process-wide ring buffer of typed events.

Reference analog: platform/profiler RecordEvent spans + the host-side
event buffers tools/timeline.py renders — unified here with the
runtime's *semantic* events (compiles, worker restarts, checkpoint
save/restore/fallback, serving dispatches, fault fires) so a slow step,
a recompile and a dataloader respawn land on ONE correlated timeline.

Design:

- Events are plain dicts in a bounded ``collections.deque`` (appends on
  a deque with ``maxlen`` are atomic under the GIL — no lock on the
  emit path; snapshots copy).  Each event carries a monotonic ``ts``,
  ``kind``, ``name``, the emitting thread id, the current training
  ``step`` correlation id (set by the static Executor per run) and
  optional ``args`` / ``dur`` / parent-span attribution.
- Spans nest per-thread: :meth:`begin_span`/:meth:`end_span` keep a
  thread-local stack so a span records its parent id even when emitted
  from RecordEvent pairs or the serving dispatcher thread.  Mismatched
  ends are tolerated (orphans are closed, never leaked).
- Export: :meth:`chrome_trace` (the trace-event JSON schema chrome://
  tracing / Perfetto load: ``ph`` X for durations, i for instants, C
  for counters) and :meth:`export_jsonl` (one JSON object per event,
  wall-clock stamped, for offline diffing).

The tracer is opt-in: ``observability.enable()`` installs one into
``core.obs_hook``; disabled, every instrumented site pays a single
module-attribute None-check.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Tracer", "EVENT_KINDS"]

# Documented event taxonomy (the "typed" in typed events).  ``emit``
# accepts any string so layers can grow new kinds without touching this
# module; exporters only special-case "counter".
EVENT_KINDS = (
    "span",             # named duration (RecordEvent, executor.run, ...)
    "op",               # one eager op dispatch (host-side duration)
    "counter",          # a counter delta next to its monitor stat
    "compile",          # an attributed XLA compile (observability.compiles)
    "worker_restart",   # DataLoader worker respawned in place
    "checkpoint",       # save / restore / fallback / preempt_*
    "serving",          # enqueue / dispatch / shed / deadline_expired
    "fault",            # an injected fault fired (testing.fault)
    "crash",            # flight-recorder dump trigger
    "perf",             # step anatomy lane (observability.perf)
    "slo",              # SLO breach / recover (observability.slo)
    "instant",          # free-form user event
)


class Tracer:
    def __init__(self, capacity: int = 8192, trace_ops: bool = True):
        self.capacity = int(capacity)
        self.trace_ops = bool(trace_ops)
        self._buf: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._step: Optional[int] = None
        self._emitted = 0
        # monotonic<->wall anchor so exports can stamp real times
        self._mono0 = time.perf_counter()
        self._wall0 = time.time()

    # -- correlation -------------------------------------------------------
    def set_step(self, step: int) -> None:
        """Set the current training-step correlation id (the static
        Executor calls this with its per-program run counter)."""
        self._step = int(step)

    @property
    def step(self) -> Optional[int]:
        return self._step

    # -- distributed trace context -----------------------------------------
    def set_trace(self, trace_id: Optional[str],
                  parent_span: Optional[str] = None) -> None:
        """Bind a distributed trace context to this thread: every event
        emitted here until :meth:`clear_trace` carries ``trace`` (and
        ``remote_parent`` when the caller handed us a parent span id
        from another process).  The serving HTTP front-end binds the
        adopted/minted ``X-Trace-Id`` around request handling; the
        engines copy the context onto queued requests so the scheduler
        threads' events inherit it via rid/sid correlation."""
        if trace_id is None:
            self.clear_trace()
            return
        self._tls.trace = (str(trace_id),
                           str(parent_span) if parent_span else None)

    def clear_trace(self) -> None:
        self._tls.trace = None

    def current_trace(self) -> Optional[str]:
        """This thread's bound trace id, or None."""
        ctx = getattr(self._tls, "trace", None)
        return ctx[0] if ctx else None

    # -- emission ----------------------------------------------------------
    def emit(self, kind: str, name: str, args: Optional[dict] = None,
             ts: Optional[float] = None, dur: Optional[float] = None,
             parent: Optional[int] = None, sid: Optional[int] = None
             ) -> int:
        """Append one event; returns its id.  ``ts`` is a
        ``time.perf_counter()`` stamp (defaults to now), ``dur`` is in
        seconds.  ``parent`` defaults to this thread's innermost open
        span, so any event emitted inside a span tree attaches to it
        without the caller threading ids through."""
        ev: Dict[str, object] = {
            "id": next(self._ids) if sid is None else sid,
            "ts": time.perf_counter() if ts is None else ts,
            "kind": kind,
            "name": name,
            "tid": threading.get_ident(),
        }
        if self._step is not None:
            ev["step"] = self._step
        if dur is not None:
            ev["dur"] = dur
        if parent is None:
            stack = getattr(self._tls, "stack", None)
            if stack:
                parent = stack[-1][0]
        if parent is not None:
            ev["parent"] = parent
        ctx = getattr(self._tls, "trace", None)
        if ctx is not None:
            ev["trace"] = ctx[0]
            if ctx[1] is not None and parent is None:
                # cross-process attribution: the root of this process's
                # subtree names the caller's span id
                ev["remote_parent"] = ctx[1]
        if args:
            ev["args"] = args
        self._emitted += 1
        self._buf.append(ev)
        return ev["id"]  # type: ignore[return-value]

    def counter(self, name: str, delta, value=None) -> None:
        """Record a counter delta (the sibling of ``monitor.stat_add``
        at instrumented sites)."""
        args = {"delta": delta}
        if value is not None:
            args["value"] = value
        self.emit("counter", name, args=args)

    def op(self, name: str, t0: float, t1: float) -> None:
        """One eager op dispatch (called from core.dispatch.apply when
        ``trace_ops``)."""
        if self.trace_ops:
            self.emit("op", name, ts=t0, dur=t1 - t0)

    # -- spans -------------------------------------------------------------
    def begin_span(self, name: str, **args) -> int:
        """Open a named span on this thread; returns the span id.  The
        span event is emitted at :meth:`end_span` (with its duration and
        its parent's id)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        sid = next(self._ids)
        parent = stack[-1][0] if stack else None
        stack.append((sid, name, time.perf_counter(), parent,
                      args or None))
        return sid

    def end_span(self, sid: int) -> None:
        """Close span ``sid``.  Spans left open above it on this
        thread's stack (a ``begin`` whose ``end`` was lost to an
        exception) are closed too, keeping parent attribution sound.
        An id not on this thread's stack (double end, or an end from a
        thread that never began it) is ignored — it must not drain the
        live spans."""
        stack = getattr(self._tls, "stack", None)
        if not stack or not any(s[0] == sid for s in stack):
            return
        now = time.perf_counter()
        while stack:
            s_id, name, t0, parent, args = stack.pop()
            self.emit("span", name, args=args, ts=t0, dur=now - t0,
                      parent=parent, sid=s_id)
            if s_id == sid:
                break

    @contextlib.contextmanager
    def span(self, name: str, **args):
        sid = self.begin_span(name, **args)
        try:
            yield sid
        finally:
            self.end_span(sid)

    # -- snapshots / export ------------------------------------------------
    def events(self, tail: Optional[int] = None) -> List[dict]:
        """Snapshot of buffered events (oldest first); ``tail`` keeps
        only the newest N."""
        evs = list(self._buf)
        if tail is not None and tail < len(evs):
            evs = evs[-tail:]
        return evs

    @property
    def emitted(self) -> int:
        """Total events emitted (>= len(events()) once the ring wraps)."""
        return self._emitted

    @property
    def dropped(self) -> int:
        """Events the full ring evicted under pressure — nonzero means
        the buffered trace is a truncated view of what was emitted.
        Derived from the emit counter (the buffer is append-only, so
        it holds exactly ``min(emitted, capacity)`` events) — per-emit
        boundary bookkeeping raced between threads and could report a
        clean tape for a truncated one."""
        return max(0, self._emitted - self.capacity)

    @property
    def high_watermark(self) -> int:
        """Most events ever buffered at once (== capacity once the
        ring has wrapped); derived like :attr:`dropped`."""
        return min(self._emitted, self.capacity)

    def ring_stats(self) -> dict:
        """Drop accounting block exporters embed next to any trace
        snapshot; also mirrors the ``obs.events_dropped`` stat and the
        capacity/high-watermark gauges into ``monitor``."""
        from ..utils import monitor
        dropped, hwm = self.dropped, self.high_watermark
        monitor.stat_set("obs.events_dropped", dropped)
        monitor.stat_set("obs.ring_capacity", self.capacity)
        monitor.stat_set("obs.ring_high_watermark", hwm)
        return {"events_emitted": self._emitted,
                "events_dropped": dropped,
                "ring_capacity": self.capacity,
                "ring_high_watermark": hwm}

    def wall_time(self, ts: float) -> float:
        """Convert a perf_counter stamp to unix wall-clock seconds."""
        return self._wall0 + (ts - self._mono0)

    def jsonable(self, ev: dict) -> dict:
        """One event as a JSON-ready dict with wall-clock timestamps."""
        out = dict(ev)
        out["time"] = round(self.wall_time(ev["ts"]), 6)
        out["ts"] = round(ev["ts"] - self._mono0, 9)
        if "dur" in out:
            out["dur"] = round(out["dur"], 9)
        return out

    def export_jsonl(self, path: Optional[str] = None) -> str:
        """JSONL dump of the buffer; writes to ``path`` when given,
        returns the text either way."""
        text = "\n".join(json.dumps(self.jsonable(e))
                         for e in self.events())
        if text:
            text += "\n"
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    def chrome_trace(self) -> dict:
        """The buffer in chrome trace-event format (load in
        chrome://tracing or ui.perfetto.dev).  Durations map to ``ph:
        "X"`` complete events, counters to ``ph: "C"``, everything else
        to ``ph: "i"`` instants."""
        pid = os.getpid()
        out = []
        for ev in self.events():
            args = dict(ev.get("args") or {})
            if "step" in ev:
                args["step"] = ev["step"]
            if "parent" in ev:
                args["parent_span"] = ev["parent"]
            if "trace" in ev:
                args["trace"] = ev["trace"]
            if "remote_parent" in ev:
                args["remote_parent"] = ev["remote_parent"]
            base = {
                "name": str(ev["name"]),
                "cat": str(ev["kind"]),
                "pid": pid,
                "tid": int(ev["tid"]),
                "ts": (ev["ts"] - self._mono0) * 1e6,   # microseconds
            }
            if ev["kind"] == "counter":
                val = args.get("value", args.get("delta", 0))
                out.append(dict(base, ph="C",
                                args={"value": float(val)}))
            elif "dur" in ev:
                out.append(dict(base, ph="X", dur=ev["dur"] * 1e6,
                                args=args))
            else:
                out.append(dict(base, ph="i", s="t", args=args))
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path
