"""Crash flight recorder: a readable black box for every bad exit.

When installed (:func:`install_flight_recorder`), three triggers dump
the last N tracer events plus a full metrics snapshot and the compile
attribution summary, atomically (``fs.write_atomic`` — a crash
mid-dump never leaves a truncated file, and the path may carry a
registered filesystem scheme):

- an :class:`~paddle_tpu.core.enforce.EnforceError` being *constructed*
  (the typed-error taxonomy every framework-detected failure passes
  through),
- an exception escaping ``Executor.run`` (both route through the
  ``core.obs_hook`` crash handler; the same exception object is only
  dumped once),
- ``SIGTERM`` — the cloud-TPU preemption notice — and any exception
  reaching ``sys.excepthook``.

The dump is a single JSON document: reason, exception (type, message,
traceback), the tracer's newest events (empty list when tracing is
off), ``monitor`` stats + histograms, and the per-cause compile
summary.  ``tools/obs_smoke.py`` gates that an injected crash leaves
one containing the injected fault event; ``testing/chaos.py`` wires it
into the chaos run so faulted training always leaves a black box.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
import weakref
from typing import Optional

from ..core import flags, obs_hook
from ..utils import monitor

__all__ = ["install_flight_recorder", "uninstall_flight_recorder",
           "dump_flight", "flight_recorder_path"]

_lock = threading.Lock()
_state: Optional[dict] = None


def flight_recorder_path() -> Optional[str]:
    """The installed recorder's dump path, or None."""
    st = _state
    return st["path"] if st is not None else None


def _dump_exc_info(exc: BaseException) -> dict:
    return {
        "type": type(exc).__name__,
        "message": str(exc),
        "traceback": traceback.format_exception(
            type(exc), exc, exc.__traceback__),
    }


def dump_flight(path: Optional[str] = None, reason: str = "manual",
                exc: Optional[BaseException] = None,
                extra: Optional[dict] = None) -> Optional[str]:
    """Write one flight dump now; returns the path (None if a dump was
    already in progress on this thread — reentrancy guard for failures
    inside the dump itself).  ``extra`` lands verbatim under the
    payload's ``"extra"`` key — the training supervisor annotates its
    kill-time dumps with the restart reason, attempt and last observed
    step this way."""
    st = _state
    if path is None:
        if st is None:
            raise ValueError("no flight recorder installed; pass path=")
        path = st["path"]
    guard = st["dumping"] if st is not None else _local_guard
    if getattr(guard, "active", False):
        return None
    guard.active = True
    try:
        trc = obs_hook._tracer
        tail = st["events"] if st is not None else 512
        events = ([trc.jsonable(e) for e in trc.events(tail=tail)]
                  if trc is not None else [])
        if trc is not None:
            trc.emit("crash", reason,
                     args={"exc": type(exc).__name__ if exc else None})
        from .compiles import explain_compiles
        from .metrics import build_info
        comp = explain_compiles()
        payload = {
            "reason": reason,
            "time": time.time(),
            "pid": os.getpid(),
            "build": build_info(),
            "exception": _dump_exc_info(exc) if exc is not None else None,
            "events": events,
            # drop accounting rides every dump: a black box whose ring
            # wrapped must say so, or the truncated tape misleads
            "obs": trc.ring_stats() if trc is not None else None,
            "stats": monitor.all_stats(),
            "histograms": monitor.all_histograms(),
            "compiles": {"total": comp["total"],
                         "unexplained": comp["unexplained"],
                         "by_cause": comp["by_cause"]},
        }
        if extra is not None:
            payload["extra"] = extra
        from . import slo as _slo
        if _slo.get_slo_monitor() is not None:
            # last evaluation, not a fresh poll — a dump mid-crash must
            # not start measuring windows
            payload["slo"] = _slo.slo_status(poll=False)
        perf = obs_hook._perf
        if perf is not None:
            payload["perf"] = perf.report()
        from ..utils import fs
        fs.write_atomic(path, json.dumps(payload, default=str).encode())
        monitor.stat_add("flight.dumps")
        return path
    finally:
        guard.active = False


_local_guard = threading.local()


def _on_crash(exc: BaseException, context: str) -> None:
    """core.obs_hook crash handler.

    Dedup is per exception OBJECT via a weakref (a raw id() would let a
    later, distinct exception reuse the freed address and be silently
    skipped) — except that a re-report of the same object that NOW
    carries a traceback upgrades the dump: EnforceError fires at
    construction (``__traceback__`` still None), and the informative
    report is the one from the raise boundary (Executor.run /
    excepthook) with the stack attached."""
    st = _state
    if st is None:
        return
    has_tb = exc.__traceback__ is not None
    prev = st["last_exc"]
    if prev is not None:
        ref, prev_had_tb = prev
        if ref() is exc and (prev_had_tb or not has_tb):
            return
    try:
        st["last_exc"] = (weakref.ref(exc), has_tb)
    except TypeError:       # exotic exception type without weakref slots
        st["last_exc"] = None
    try:
        dump_flight(reason=context, exc=exc)
    except Exception:       # the recorder must never mask the crash
        pass


def _excepthook(exc_type, exc, tb):
    st = _state
    if st is not None:
        if exc is not None and exc.__traceback__ is None:
            exc = exc.with_traceback(tb)
        _on_crash(exc, "unhandled_exception")
        prev = st["prev_excepthook"]
    else:
        prev = sys.__excepthook__
    prev(exc_type, exc, tb)


def install_flight_recorder(path: Optional[str] = None, events: int = 512,
                            catch_sigterm: bool = True,
                            catch_excepthook: bool = True) -> str:
    """Arm the flight recorder; returns the dump path.

    ``path`` defaults to ``FLAGS_flight_recorder_path`` (or
    ``./flight_record.json``).  ``events`` bounds how many tracer
    events each dump carries.  SIGTERM hooking chains to the previous
    handler (the checkpoint preemption handler keeps working) and is
    skipped off the main thread."""
    global _state
    with _lock:
        if _state is not None:
            _uninstall_locked()
        path = (path or flags.get_flag("flight_recorder_path")
                or "flight_record.json")
        st = {
            "path": path,
            "events": int(events),
            "last_exc": None,
            "dumping": threading.local(),
            "prev_excepthook": None,
            "restore_sigterm": None,
        }
        _state = st
        obs_hook.set_crash_handler(_on_crash)
        if catch_excepthook:
            st["prev_excepthook"] = sys.excepthook
            sys.excepthook = _excepthook
        if catch_sigterm:
            from ..utils.checkpoint import install_preemption_handler
            st["restore_sigterm"] = install_preemption_handler(
                lambda: dump_flight(reason="SIGTERM"))
        return path


def _uninstall_locked() -> None:
    global _state
    st = _state
    if st is None:
        return
    _state = None
    if obs_hook.crash_handler() is _on_crash:
        obs_hook.set_crash_handler(None)
    if st["prev_excepthook"] is not None and sys.excepthook is _excepthook:
        sys.excepthook = st["prev_excepthook"]
    if st["restore_sigterm"] is not None:
        st["restore_sigterm"]()


def uninstall_flight_recorder() -> None:
    with _lock:
        _uninstall_locked()
