"""Recompile attribution: every XLA compile gets a named cause.

The three compiling layers — the static Executor, the jit
(to_static) cache, and the inference Predictor — report each compile
here with a structured *signature* (an ordered dict of the cache-key
components that could have forced it).  Attribution is central: the
previous signature for the same (component, identity) is diffed against
the new one, the first changed field (in the caller's significance
order) names the cause — ``new_program_version``, ``new_feed_signature``,
``new_bucket``, ... — and the diff itself is kept so
:func:`explain_compiles` can show *what* changed, not just that
something did.  A compile whose signature matches its predecessor
exactly is ``unexplained`` — the smoke gate (tools/obs_smoke.py)
asserts that count stays 0.

Always on: compiles are rare and cost seconds, so attribution is not
gated behind ``observability.enable()`` — only the tracer *event* per
compile is.  Each record also counts ``compiles.<component>.<cause>``
and ``compiles.total`` in monitor, so bench/CI trajectories explain
perf deltas per cause.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core import obs_hook
from ..utils import monitor

__all__ = ["record_compile", "explain_compiles", "reset_compiles",
           "annotate_compile"]

_MAX_RECORDS = 2048          # ring of full records; totals never drop

_lock = threading.Lock()
_records: collections.deque = collections.deque(maxlen=_MAX_RECORDS)
_prev: Dict[Tuple[str, object], dict] = {}
_totals: collections.Counter = collections.Counter()


def _freeze(v):
    """Signature values must be hashable/comparable; stringify the rest."""
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return v
    if isinstance(v, (tuple, list)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(_freeze(x) for x in v))
    return repr(v)


def record_compile(component: str, identity, signature: Dict[str, object],
                   note: str = "", predicted: Optional[dict] = None,
                   kernels: Optional[List[str]] = None,
                   comm: Optional[dict] = None,
                   cache: Optional[str] = None) -> dict:
    """Report one compile.

    ``component``: "executor" | "jit" | "predictor" | ... .
    ``identity``: what recompiles are diffed against — the program
    serial, the StaticFunction instance serial, the Predictor serial.
    ``signature``: ordered cache-key components, most significant
    first; the first field that differs from the previous compile of
    the same identity names the cause (``new_<field>``).
    ``predicted``: the static cost model's numbers for the compiled
    step (FLOPs, peak bytes — static/analysis/cost.compile_summary);
    kept on the record but deliberately OUT of the signature, so a
    cost-model change can never masquerade as a recompile cause.
    ``explain_compiles`` surfaces it next to the attribution, which is
    where predicted-vs-measured drift shows up.
    ``kernels``: the Pallas-tier kernels this executable selected
    (realized fusion-candidate epilogues, fused Adam) — like
    ``predicted``, on the record but OUT of the signature: flipping
    the tier recompiles via its own cache-key field, never as an
    attribution mystery, and the perf observatory can attribute a
    step-time delta to kernel on/off by reading the record.
    ``comm``: the grad-comm bucket schedule this executable lowered
    (per-bucket size/algorithm/wire/issue point + the resolved overlap
    path) — on the record, OUT of the signature (knob flips recompile
    through the plan fingerprint's ``sharding`` field), so overlap
    decisions are auditable from ``explain_compiles()``.
    ``cache``: persistent-compile-cache provenance — ``"loaded"`` (the
    executable was deserialized from ``FLAGS_compile_cache_dir``,
    no XLA compile happened), ``"compiled"`` (fresh compile, stored for
    next time), or ``"rejected:<why>"`` (a cache entry existed but its
    version/topology stamp or device fingerprint mismatched; fresh
    compile).  OUT of the signature for the same reason as the others:
    cache state must never masquerade as a recompile cause.
    """
    sig = {k: _freeze(v) for k, v in signature.items()}
    now = time.time()
    with _lock:
        prev = _prev.get((component, identity))
        if prev is None:
            cause = "first_compile"
            changed: Dict[str, tuple] = {}
        else:
            changed = {k: (prev.get(k), v) for k, v in sig.items()
                       if prev.get(k) != v}
            if changed:
                cause = "new_" + next(k for k in sig if k in changed)
            else:
                cause = "unexplained"
        _prev[(component, identity)] = sig
        rec = {
            "time": now,
            "component": component,
            "identity": identity,
            "cause": cause,
            "changed": changed,
            "signature": sig,
        }
        if note:
            rec["note"] = note
        if predicted:
            rec["predicted"] = dict(predicted)
        if kernels:
            rec["kernels"] = list(kernels)
        if comm:
            rec["comm"] = dict(comm)
        if cache:
            rec["cache"] = str(cache)
        _records.append(rec)
        _totals[(component, cause)] += 1
    monitor.stat_add(f"compiles.{component}.{cause}")
    monitor.stat_add("compiles.total")
    trc = obs_hook._tracer
    if trc is not None:
        trc.emit("compile", f"{component}.compile",
                 args={"cause": cause, "identity": str(identity),
                       "changed": sorted(changed)})
    return rec


def annotate_compile(component: str, identity, cache: str) -> bool:
    """Attach cache provenance to the NEWEST record of ``(component,
    identity)`` after the fact.  The lazily-compiling Executor records
    its compile when the cache key misses but only learns loaded-vs-
    compiled at the first dispatch — this closes that gap so
    ``explain_compiles()`` shows provenance for every site.  Returns
    False when no record matches (nothing to annotate)."""
    with _lock:
        for rec in reversed(_records):
            if (rec["component"] == component
                    and rec["identity"] == identity):
                rec["cache"] = str(cache)
                return True
    return False


def explain_compiles(component: Optional[str] = None) -> dict:
    """Why did every compile happen?

    Returns ``{"total", "unexplained", "by_cause": {"component.cause":
    n}, "records": [...]}`` — ``records`` keeps the newest
    ``_MAX_RECORDS`` full entries (cause + field-level diff), the
    totals cover the whole process lifetime.  ``component`` filters
    both."""
    with _lock:
        recs = [dict(r) for r in _records
                if component is None or r["component"] == component]
        totals = {f"{c}.{cause}": n for (c, cause), n in _totals.items()
                  if component is None or c == component}
    total = sum(totals.values())
    unexplained = sum(n for k, n in totals.items()
                      if k.endswith(".unexplained"))
    return {"total": total, "unexplained": unexplained,
            "by_cause": dict(sorted(totals.items())), "records": recs}


def reset_compiles() -> None:
    """Drop attribution history (tests / fresh smoke runs)."""
    with _lock:
        _records.clear()
        _prev.clear()
        _totals.clear()
