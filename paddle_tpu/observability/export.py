"""Per-process telemetry exporter: spool metrics + trace for the fleet.

Every observability surface in this tree is per-process; the fleet
(supervised training children, serving replicas, their parents) needs
one view.  This module is the producing half: when
``FLAGS_obs_spool_dir`` is set the process periodically spools

- ``meta.json`` — role, pid, start time and :func:`..metrics.build_info`
  (the fleet view diffs the build block across processes to flag
  version skew), written once at install;
- ``metrics.json`` — the latest :func:`..metrics.metrics_snapshot`,
  atomically overwritten each flush;
- ``trace-NNNNNN.json`` — tracer-ring segments: the events emitted
  since the previous flush, wall-clock stamped (``Tracer.jsonable``) so
  the aggregator (:mod:`.fleet`) can align lanes across processes
  whose monotonic clocks share no epoch;

into ``<spool_dir>/<role>-<pid>/``, each document wrapped as
``{"sha256": ..., "body": ...}`` and written via ``fs.write_atomic`` —
a reader never sees a torn file, and a corrupt one is detected, not
merged.

Enablement follows the supervisor ``child_env`` staging: the parent
sets ``FLAGS_obs_spool_dir`` (env), supervisors forward it (plus a
per-incarnation ``FLAGS_obs_role``) into every child they spawn, and
``paddle_tpu/__init__`` installs the exporter at import when the flag
is set — children export with zero code changes.  Off, instrumented
hot paths pay one module-attribute None-check on ``obs_hook._export``
(the same contract as ``_tracer``/``_perf``/``_heartbeat``).

Flush cadence: a daemon thread fires every
``FLAGS_obs_export_interval_s``; hot paths also call :meth:`tick`
(rate-limited to a time comparison) so a process that dies between
timer fires — the chaos drills kill children with SIGKILL — still
leaves a spool no older than one interval of work.  A final flush runs
at interpreter exit for clean shutdowns.
"""
from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
from typing import Optional

from ..core import flags, obs_hook

__all__ = ["TelemetryExporter", "install_exporter", "uninstall_exporter",
           "get_exporter", "checksum_wrap", "checksum_unwrap"]


def checksum_wrap(body: dict) -> bytes:
    """Serialize ``body`` with an embedded sha256 over its canonical
    JSON form."""
    text = json.dumps(body, sort_keys=True, default=str)
    digest = hashlib.sha256(text.encode()).hexdigest()
    return json.dumps({"sha256": digest, "body": json.loads(text)},
                      sort_keys=True).encode()


def checksum_unwrap(data: bytes) -> dict:
    """Parse a :func:`checksum_wrap` document, verifying the digest.
    Raises ``ValueError`` on a missing or mismatched checksum."""
    doc = json.loads(data)
    if not isinstance(doc, dict) or "sha256" not in doc:
        raise ValueError("not a checksummed telemetry document")
    body = doc.get("body")
    text = json.dumps(body, sort_keys=True, default=str)
    digest = hashlib.sha256(text.encode()).hexdigest()
    if digest != doc["sha256"]:
        raise ValueError(
            f"telemetry checksum mismatch: {doc['sha256']} != {digest}")
    return body


class TelemetryExporter:
    """Spools this process's metrics + trace segments for the fleet
    aggregator.  Install via :func:`install_exporter` (or let
    ``paddle_tpu/__init__`` do it from ``FLAGS_obs_spool_dir``)."""

    def __init__(self, spool_dir: str, role: Optional[str] = None,
                 interval_s: Optional[float] = None):
        self.role = str(role or flags.get_flag("obs_role") or "proc")
        self.pid = os.getpid()
        self.interval_s = max(0.05, float(
            interval_s if interval_s is not None
            else flags.get_flag("obs_export_interval_s")))
        self.dir = os.path.join(str(spool_dir),
                                f"{self.role}-{self.pid}")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._last_flush = 0.0          # first tick() flushes
        self._spooled_ids: set = set()  # ids already segmented, bounded
                                        # by the ring (reset to its
                                        # current contents each flush)
        self._seq = 0
        self.flushes = 0
        self.errors = 0
        self._closed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._write_meta()

    # -- spool writers -----------------------------------------------------
    def _write(self, name: str, body: dict) -> None:
        from ..utils import fs
        fs.write_atomic(os.path.join(self.dir, name),
                        checksum_wrap(body))

    def _write_meta(self) -> None:
        from .metrics import build_info
        self._write("meta.json", {
            "role": self.role,
            "pid": self.pid,
            "start_time": time.time(),
            "interval_s": self.interval_s,
            "build": build_info(),
        })

    def tick(self, now: Optional[float] = None) -> bool:
        """Hot-path entry: flush if an interval has passed since the
        last flush, else return immediately (one time comparison).
        Returns whether a flush happened."""
        now = time.monotonic() if now is None else now
        if now - self._last_flush < self.interval_s:
            return False
        return self.flush(now=now)

    def flush(self, now: Optional[float] = None) -> bool:
        """Spool the latest metrics snapshot and any new tracer events
        now.  Never raises (a telemetry failure must not take down the
        process it observes); failures are counted on ``errors``."""
        with self._lock:
            if self._closed:
                return False
            self._last_flush = (time.monotonic() if now is None
                                else now)
            try:
                self._flush_locked()
                self.flushes += 1
                return True
            except Exception:
                self.errors += 1
                return False

    def _flush_locked(self) -> None:
        from .metrics import metrics_snapshot
        self._write("metrics.json", {
            "role": self.role, "pid": self.pid,
            "snapshot": metrics_snapshot(),
        })
        trc = obs_hook._tracer
        if trc is None:
            return
        # "new since last flush" by event id, not position: a span's
        # event carries the id allocated at begin_span but is emitted
        # at end_span, so a long span lands out of id order and a
        # high-watermark filter would drop it
        evs = trc.events()
        fresh = [trc.jsonable(e) for e in evs
                 if e["id"] not in self._spooled_ids]
        self._spooled_ids = {e["id"] for e in evs}
        if not fresh:
            return
        self._seq += 1
        self._write(f"trace-{self._seq:06d}.json", {
            "role": self.role, "pid": self.pid, "seq": self._seq,
            "events": fresh,
        })

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TelemetryExporter":
        """Arm the periodic flush thread and the exit-time flush."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="obs-export", daemon=True)
            self._thread.start()
            atexit.register(self.close)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()

    def close(self) -> None:
        """Final flush + stop the timer thread.  Idempotent."""
        self._stop.set()
        self.flush()
        with self._lock:
            self._closed = True


def install_exporter(spool_dir: Optional[str] = None,
                     role: Optional[str] = None,
                     interval_s: Optional[float] = None
                     ) -> Optional[TelemetryExporter]:
    """Install (and return) the process telemetry exporter.

    ``spool_dir`` defaults to ``FLAGS_obs_spool_dir``; with neither
    set this is a no-op returning None.  If no tracer is live one is
    enabled — a spool without a trace lane defeats the point — and the
    exporter lands in ``obs_hook._export`` for hot-path ticks."""
    spool_dir = spool_dir or flags.get_flag("obs_spool_dir")
    if not spool_dir:
        return None
    prev = obs_hook._export
    if prev is not None:
        prev.close()
    if obs_hook._tracer is None:
        from . import enable
        enable()
    exp = TelemetryExporter(spool_dir, role=role,
                            interval_s=interval_s).start()
    obs_hook.set_export(exp)
    exp.flush()
    return exp


def uninstall_exporter() -> None:
    exp = obs_hook._export
    obs_hook.set_export(None)
    if exp is not None:
        exp.close()


def get_exporter() -> Optional[TelemetryExporter]:
    return obs_hook._export
