"""Runtime performance observatory: step anatomy, memory, drift.

PR-5's tracer records that things happened and the static cost model
(static/analysis/cost.py) predicts what *should* happen; this module
closes the loop at runtime:

- **Step-time anatomy** — the static Executor (and the serving
  engines) report per-step host time (feed conversion + dispatch
  submit) on every step, and *device* time on a sampled subset: every
  ``sample_every``-th step per compile identity is fenced with
  ``jax.block_until_ready`` so the wall from dispatch to results-ready
  is measured.  Unsampled steps stay fully asynchronous — sampling is
  what keeps the donated async pipeline intact while still yielding a
  device-time distribution (``step.host_ms`` / ``step.device_ms``
  monitor histograms + ``perf`` tracer lanes).
- **Device-memory telemetry** — on each fenced sample the live jax
  buffers are sized per device (per-shard via ``addressable_shards``
  when a mesh is live), exported as ``mem.device.<id>.live_bytes`` /
  ``.peak_live_bytes`` gauges and compared against the compile
  record's predicted peak.
- **Drift tracker** — per compile identity, a rolling window of
  measured step times / peak bytes is compared to the cost model's
  prediction (the ``predicted`` dict ``record_compile`` carries);
  :func:`perf_report` renders totals, per-identity drift %% and the
  worst offenders (``tools/perf_report.py`` is the CLI).
- **Exposed-vs-hidden comm split** — when the compile record predicts
  gradient-collective seconds (grad_comm under a sharding plan), each
  fenced step is split into comm that hid behind backward and comm
  that extended the step (``comm.exposed_ms`` / ``comm.hidden_ms``
  histograms + a per-identity ``comm`` block in the report).  Under
  ``overlap='none'`` the split is structural (hidden == 0 — the
  lowering barriers comm after backward); on overlapping paths the
  exposed share is learned from the fence, so a *scheduling*
  regression (collectives sliding out from behind backward) moves
  drift even when every kernel is as fast as ever.

Disabled-path contract (the PR-5 rule): when the observatory is off,
every instrumented site pays ONE module-attribute None-check
(``core.obs_hook._perf``) — no imports, no calls, no timestamps.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from ..core import flags, obs_hook
from ..utils import monitor

__all__ = ["PerfObservatory", "enable_perf", "disable_perf",
           "perf_enabled", "get_perf", "perf_report",
           "render_perf_report", "device_memory"]

_DEVICE_SAMPLES = 128       # rolling window of fenced samples kept
_MAX_IDENTITIES = 256       # LRU cap on tracked compile identities —
                            # the Executor evicts stale-version cache
                            # entries but their identities would
                            # otherwise accumulate here forever


def device_memory() -> Dict[str, dict]:
    """Live jax buffer bytes per device, sized shard-wise.

    Walks ``jax.live_arrays()`` and attributes each addressable shard's
    bytes to the device that holds it — under a mesh every chip is
    charged only for the shards it actually stores, not the global
    array.  Returns ``{device_label: {"live_bytes", "arrays"}}``.
    """
    import jax
    per: Dict[str, dict] = {}
    for a in jax.live_arrays():
        try:
            shards = a.addressable_shards
        except Exception:           # deleted/donated buffer mid-walk
            continue
        for sh in shards:
            try:
                d = sh.device
                nbytes = sh.data.nbytes
            except Exception:
                continue
            key = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            slot = per.setdefault(key, {"live_bytes": 0, "arrays": 0})
            slot["live_bytes"] += int(nbytes)
            slot["arrays"] += 1
    return per


def _comm_split_s(predicted: Optional[dict], measured_s: Optional[float]
                  ) -> Optional[dict]:
    """Exposed-vs-hidden comm split of one step, in seconds, from the
    compile record's overlap prediction plus (when available) a fenced
    measurement.

    ``overlap == 'none'`` is structural: the lowering barriers the comm
    stage after backward, so exposed == total and hidden == 0 by
    construction, never by measurement.  On an overlapping path the
    exposed share is *learned* from the fence: whatever the measured
    step ran beyond the compute-only prediction is attributed to
    exposed comm, clamped to [0, total comm] — an upper bound (queue
    backlog and model error land in it too, which is exactly what
    drift tracking wants to catch: a scheduling regression shows up as
    exposed comm growing toward total).  Without a measurement the
    predicted split is reported."""
    if not predicted:
        return None
    comm_s = predicted.get("predicted_comm_s")
    if not comm_s:
        return None
    path = predicted.get("comm_overlap", "none")
    exposed_pred = predicted.get("predicted_exposed_comm_s", comm_s)
    if path == "none":
        exposed = comm_s
    elif measured_s is not None:
        compute_s = max(0.0, (predicted.get("predicted_step_s") or 0.0)
                        - exposed_pred)
        exposed = min(comm_s, max(0.0, measured_s - compute_s))
    else:
        exposed = min(comm_s, exposed_pred)
    return {"comm_s": comm_s, "exposed_s": exposed,
            "hidden_s": comm_s - exposed, "overlap": path,
            "predicted_exposed_s": exposed_pred}


def _predicted_step_s(predicted: Optional[dict]) -> Optional[float]:
    """Predicted step seconds for a compile record's ``predicted``
    dict: taken verbatim when the record carries ``predicted_step_s``,
    re-derived from FLOPs / min traffic against the roofline chip spec
    (``FLAGS_perf_chip``, auto-detected backend by default) otherwise."""
    if not predicted:
        return None
    if predicted.get("predicted_step_s"):
        return float(predicted["predicted_step_s"])
    flops = predicted.get("flops")
    traffic = predicted.get("min_traffic_bytes")
    if not flops and not traffic:
        return None
    from ..static.analysis.cost import CHIP_SPECS, resolve_perf_chip
    spec = CHIP_SPECS.get(resolve_perf_chip())
    if spec is None:
        return None
    return max((flops or 0) / spec.peak_flops,
               (traffic or 0) / spec.hbm_bw)


class _IdentityPerf:
    """Rolling measured-vs-predicted state for one compile identity."""

    __slots__ = ("component", "identity", "steps", "sampled",
                 "host_sum_s", "device_s", "peak_bytes", "predicted")

    def __init__(self, component: str, identity):
        self.component = component
        self.identity = identity
        self.steps = 0
        self.sampled = 0
        self.host_sum_s = 0.0
        self.device_s: collections.deque = collections.deque(
            maxlen=_DEVICE_SAMPLES)
        self.peak_bytes = 0
        self.predicted: Optional[dict] = None

    def drift(self) -> dict:
        """Measured vs predicted, as the report shows it.  Drift %% is
        ``(measured - predicted) / predicted * 100`` — positive =
        slower / bigger than the model predicted.  ``peak_bytes`` is
        the max per-device live bytes observed at THIS identity's
        fences — ``jax.live_arrays()`` is process-wide, so with several
        programs or engines resident the number is an upper bound on
        this identity's own footprint, not an attribution."""
        out: dict = {
            "component": self.component,
            "identity": self.identity,
            "steps": self.steps,
            "sampled": self.sampled,
            "host_ms_mean": (self.host_sum_s / self.steps * 1e3
                             if self.steps else None),
        }
        measured: dict = {}
        if self.device_s:
            srt = sorted(self.device_s)
            measured["step_ms_p50"] = srt[len(srt) // 2] * 1e3
            measured["step_ms_min"] = srt[0] * 1e3
            measured["step_ms_max"] = srt[-1] * 1e3
        if self.peak_bytes:
            measured["peak_bytes"] = self.peak_bytes
        out["measured"] = measured
        out["predicted"] = dict(self.predicted) if self.predicted else None
        drift: dict = {}
        pstep = _predicted_step_s(self.predicted)
        if pstep and measured.get("step_ms_p50"):
            drift["step_time_pct"] = (
                (measured["step_ms_p50"] / 1e3 - pstep) / pstep * 100.0)
            out["predicted_step_ms"] = pstep * 1e3
        ppeak = (self.predicted or {}).get("peak_bytes_per_shard") \
            or (self.predicted or {}).get("peak_bytes")
        if ppeak and self.peak_bytes:
            drift["peak_bytes_pct"] = (
                (self.peak_bytes - ppeak) / ppeak * 100.0)
        out["drift"] = drift
        split = _comm_split_s(
            self.predicted,
            (measured["step_ms_p50"] / 1e3
             if measured.get("step_ms_p50") is not None else None))
        if split is not None:
            out["comm"] = {
                "overlap": split["overlap"],
                "comm_ms": split["comm_s"] * 1e3,
                "exposed_ms": split["exposed_s"] * 1e3,
                "hidden_ms": split["hidden_s"] * 1e3,
                "predicted_exposed_ms":
                    split["predicted_exposed_s"] * 1e3,
            }
        return out


class PerfObservatory:
    """Process-wide runtime performance observatory.

    Install with :func:`enable_perf`; instrumented sites reach it
    through ``core.obs_hook._perf`` (one None-check when off).

    Args:
        sample_every: fence + memory-sample every Nth step per compile
            identity (default ``FLAGS_perf_sample_every``).  ``<= 0``
            disables fencing — host anatomy only.
        memory: take device-memory samples on fenced steps.
    """

    def __init__(self, sample_every: Optional[int] = None,
                 memory: bool = True):
        self.sample_every = int(
            flags.get_flag("perf_sample_every") if sample_every is None
            else sample_every)
        self.memory = bool(memory)
        # reentrant: dump_flight embeds report() from the SIGTERM
        # handler, which can interrupt the SAME thread mid-step()
        # inside this lock — a plain Lock would self-deadlock the
        # crash path whose whole purpose is reliability at preemption
        self._lock = threading.RLock()
        self._ids: "collections.OrderedDict[tuple, _IdentityPerf]" = \
            collections.OrderedDict()
        self._ids_evicted = 0
        self._dev_peak: Dict[str, int] = {}
        self._serving_steps: Dict[str, int] = {}

    # -- executor step anatomy --------------------------------------------
    def step(self, component: str, identity, t_feed0: float,
             host_feed_s: float, t_disp0: float, dispatch_s: float,
             fetches, predicted: Optional[dict] = None) -> None:
        """One executor step.  ``t_feed0``/``t_disp0`` are the
        perf_counter stamps at feed-conversion and dispatch start;
        ``fetches`` is the async result to fence on sampled steps."""
        with self._lock:
            key = (component, identity)
            idp = self._ids.get(key)
            if idp is None:
                idp = self._ids[key] = _IdentityPerf(component, identity)
                if len(self._ids) > _MAX_IDENTITIES:
                    self._ids.popitem(last=False)   # least recent
                    self._ids_evicted += 1
            else:
                self._ids.move_to_end(key)
            idp.steps += 1
            n = idp.steps
            host_s = host_feed_s + dispatch_s
            idp.host_sum_s += host_s
            if predicted is not None:
                idp.predicted = predicted
            fence = self.sample_every > 0 and n % self.sample_every == 0
            if fence:
                idp.sampled += 1
        monitor.stat_observe("step.host_ms", host_s * 1e3)
        trc = obs_hook._tracer
        if trc is not None:
            # host lanes as two truthful intervals: feed conversion
            # and dispatch submit are separated by cache-lookup/state
            # work, so one span of their summed duration would end
            # mid-gap and never overlap the device span it pairs with
            trc.emit("perf", "step.host.feed", ts=t_feed0,
                     dur=host_feed_s, args={"identity": str(identity)})
            trc.emit("perf", "step.host.dispatch", ts=t_disp0,
                     dur=dispatch_s, args={"identity": str(identity)})
        if not fence:
            return
        import jax
        jax.block_until_ready(fetches)
        device_s = time.perf_counter() - t_disp0
        with self._lock:
            idp.device_s.append(device_s)
        monitor.stat_observe("step.device_ms", device_s * 1e3)
        monitor.stat_add("perf.fences")
        # exposed-vs-hidden comm split per fenced step: when the compile
        # record predicted gradient-collective seconds, attribute this
        # step's wall beyond the compute-only prediction to exposed comm
        # (structurally all-exposed under overlap='none')
        split = _comm_split_s(idp.predicted, device_s)
        if split is not None:
            monitor.stat_observe("comm.exposed_ms",
                                 split["exposed_s"] * 1e3)
            monitor.stat_observe("comm.hidden_ms",
                                 split["hidden_s"] * 1e3)
        if trc is not None:
            # device lane: dispatch start -> results ready.  Includes
            # any queue backlog the async pipeline had built — the
            # number answers "how long until this step's results
            # exist", which is what drift is measured against.
            trc.emit("perf", "step.device", ts=t_disp0, dur=device_s,
                     args={"identity": str(identity), "step": n})
        if self.memory:
            self._sample_memory(idp)

    # -- serving anatomy ---------------------------------------------------
    def serving_step(self, engine: Optional[str], kind: str,
                     dur_s: float) -> None:
        """One serving dispatch / decode step (already host-synced by
        the engine).  ``engine`` is the engine's ``name`` — None when
        unnamed, never a sentinel string, so an engine literally named
        ``"default"`` still gets its mirror.  Feeds the process-wide
        step histogram — mirrored per named engine
        (``perf.serving.<engine>.<kind>_ms``), so a multi-model
        process can tell a slow engine from a fast one — and the
        memory sampler on the observatory cadence."""
        monitor.stat_observe(f"perf.serving.{kind}_ms", dur_s * 1e3)
        if engine:
            monitor.stat_observe(f"perf.serving.{engine}.{kind}_ms",
                                 dur_s * 1e3)
        with self._lock:
            # cadence per (engine, kind): an unnamed InferenceEngine
            # and unnamed GenerationEngine both pass engine=None and
            # would otherwise share one counter, sampling memory at
            # ~2x the configured rate off the interleaved count
            ck = (engine, kind)
            n = self._serving_steps.get(ck, 0) + 1
            self._serving_steps[ck] = n
        if self.memory and self.sample_every > 0 \
                and n % self.sample_every == 0:
            self._sample_memory(None)

    # -- device memory -----------------------------------------------------
    def _sample_memory(self, idp: Optional[_IdentityPerf]) -> None:
        per = device_memory()
        total = 0
        peak_dev = 0
        with self._lock:
            for key, slot in per.items():
                b = slot["live_bytes"]
                total += b
                peak_dev = max(peak_dev, b)
                prev = self._dev_peak.get(key, 0)
                if b > prev:
                    self._dev_peak[key] = b
                monitor.stat_set(f"mem.device.{key}.live_bytes", b)
                monitor.stat_set(f"mem.device.{key}.peak_live_bytes",
                                 max(b, prev))
            if idp is not None and peak_dev > idp.peak_bytes:
                idp.peak_bytes = peak_dev
        monitor.stat_set("mem.live_bytes_total", total)
        trc = obs_hook._tracer
        if trc is not None:
            trc.counter("mem.live_bytes_total", 0, value=total)

    def memory_snapshot(self) -> dict:
        """Current + peak live bytes per device label."""
        per = device_memory()
        with self._lock:
            peaks = dict(self._dev_peak)
        return {key: {"live_bytes": slot["live_bytes"],
                      "arrays": slot["arrays"],
                      "peak_live_bytes": max(peaks.get(key, 0),
                                             slot["live_bytes"])}
                for key, slot in per.items()}

    # -- reporting ---------------------------------------------------------
    def report(self) -> dict:
        """The drift report: totals, per-identity measured-vs-predicted
        drift %%, worst offenders first (``explain_compiles``-style)."""
        with self._lock:
            ids = [idp.drift() for idp in self._ids.values()]
            peaks = dict(self._dev_peak)
        ids.sort(key=lambda r: abs(r["drift"].get("step_time_pct", 0.0)),
                 reverse=True)
        return {
            "enabled": True,
            "sample_every": self.sample_every,
            "totals": {
                "identities": len(ids),
                "identities_evicted": self._ids_evicted,
                "steps": sum(r["steps"] for r in ids),
                "sampled": sum(r["sampled"] for r in ids),
            },
            "identities": ids,
            "worst": [f"{r['component']}#{r['identity']}" for r in ids
                      if r["drift"].get("step_time_pct") is not None][:5],
            "devices": {k: {"peak_live_bytes": v}
                        for k, v in peaks.items()},
        }


def enable_perf(sample_every: Optional[int] = None,
                memory: bool = True) -> PerfObservatory:
    """Install (and return) a fresh process-wide observatory."""
    p = PerfObservatory(sample_every=sample_every, memory=memory)
    obs_hook.set_perf(p)
    return p


def disable_perf() -> None:
    """Remove the observatory; instrumented sites return to the one
    None-check disabled path."""
    obs_hook.set_perf(None)


def perf_enabled() -> bool:
    return obs_hook._perf is not None


def get_perf() -> Optional[PerfObservatory]:
    return obs_hook._perf


def perf_report() -> dict:
    """The installed observatory's drift report (``{"enabled": False}``
    when the observatory is off)."""
    p = obs_hook._perf
    if p is None:
        return {"enabled": False}
    return p.report()


def _fmt_pct(v) -> str:
    return "n/a" if v is None else f"{v:+.1f}%"


def render_perf_report(rep: Optional[dict] = None) -> str:
    """Human-readable drift report (the CLI's output)."""
    rep = perf_report() if rep is None else rep
    if not rep.get("enabled"):
        return "perf observatory: disabled (observability.enable_perf())"
    t = rep["totals"]
    lines = [
        f"perf observatory: {t['identities']} compile identities, "
        f"{t['steps']} steps, {t['sampled']} fenced samples "
        f"(every {rep['sample_every']})"]
    for r in rep["identities"]:
        m = r["measured"]
        d = r["drift"]
        lines.append(
            f"  {r['component']}#{r['identity']}: steps={r['steps']} "
            f"host {r['host_ms_mean']:.3f} ms/step" if r["host_ms_mean"]
            is not None else
            f"  {r['component']}#{r['identity']}: steps={r['steps']}")
        if m.get("step_ms_p50") is not None:
            pred = (f", predicted {r['predicted_step_ms']:.3f} ms "
                    f"(drift {_fmt_pct(d.get('step_time_pct'))})"
                    if r.get("predicted_step_ms") else "")
            lines.append(
                f"    device p50 {m['step_ms_p50']:.3f} ms "
                f"[{m['step_ms_min']:.3f}, {m['step_ms_max']:.3f}]{pred}")
        if m.get("peak_bytes"):
            p = r.get("predicted") or {}
            ppeak = p.get("peak_bytes_per_shard") or p.get("peak_bytes")
            pred = (f", predicted {ppeak} "
                    f"(drift {_fmt_pct(d.get('peak_bytes_pct'))})"
                    if ppeak else "")
            lines.append(f"    peak live bytes {m['peak_bytes']}{pred}")
        c = r.get("comm")
        if c is not None:
            lines.append(
                f"    comm {c['comm_ms']:.3f} ms "
                f"(exposed {c['exposed_ms']:.3f} / hidden "
                f"{c['hidden_ms']:.3f}, overlap={c['overlap']}, "
                f"predicted exposed {c['predicted_exposed_ms']:.3f})")
    for dev, slot in sorted(rep.get("devices", {}).items()):
        lines.append(f"  device {dev}: peak live "
                     f"{slot['peak_live_bytes']} bytes")
    if rep.get("worst"):
        lines.append(f"  worst step-time drift: "
                     f"{', '.join(rep['worst'])}")
    return "\n".join(lines)
