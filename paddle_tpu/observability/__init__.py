"""paddle_tpu.observability — unified tracing, metrics and post-mortems.

The reproduction's four telemetry islands (profiler host spans,
``utils.monitor`` gauges/histograms, the serving ``/metrics`` endpoint,
``fault.fired.*`` counters) correlate here:

- :func:`enable` installs a process-wide :class:`Tracer` — a ring
  buffer of typed events (spans, eager op dispatches, compiles, worker
  restarts, checkpoint save/restore/fallback, serving dispatches,
  fault fires) with step/request correlation ids, exportable as
  chrome-trace JSON or JSONL.  Disabled (the default), every
  instrumented hot path pays one module-attribute None-check
  (``core.obs_hook``, same pattern as ``core.profiler_hook``).
- :func:`explain_compiles` attributes every XLA compile the static
  Executor, the jit layer and the inference Predictor performed to a
  named cause (new program version, new feed signature, new bucket,
  ...) with a diff against the previous signature — always on, counted
  per-cause in ``monitor``.
- :func:`prometheus_text` / :func:`metrics_snapshot` /
  :func:`dump_metrics` export the whole monitor registry as Prometheus
  text exposition or JSON (``serving/http.py`` content-negotiates
  ``/metrics``; ``hapi.callbacks.MetricsDump`` +
  ``FLAGS_metrics_dump_path`` append JSONL from training).
- :func:`install_flight_recorder` arms the crash flight recorder:
  EnforceError / executor exceptions / SIGTERM / sys.excepthook dump
  the last N events + full metrics snapshot atomically for post-mortem.
- :func:`enable_perf` installs the runtime performance observatory
  (:mod:`.perf`): sampled step-time anatomy (host vs device lanes),
  per-device live/peak memory gauges, and a rolling
  predicted-vs-measured drift tracker surfaced by :func:`perf_report`.
- :func:`install_slo_monitor` (:mod:`.slo`) evaluates declarative
  :class:`SLORule` rolling-window burn-rate rules over the monitor
  registry; :func:`slo_status` drives ``/healthz`` degradation and the
  ``paddle_tpu_slo_*`` Prometheus gauges.
- :func:`install_exporter` (:mod:`.export`) spools this process's
  metrics + trace segments under ``FLAGS_obs_spool_dir`` for the fleet
  aggregator (:mod:`.fleet`): :func:`fleet_snapshot`,
  :func:`fleet_prometheus_text` (one exposition with ``proc`` labels),
  :func:`merged_chrome_trace` (one timeline, a lane per process),
  :func:`assemble_trace` (one distributed request's span tree) and
  :class:`FleetView` behind ``GET /admin/fleet``.
"""
from __future__ import annotations

import contextlib
from typing import Optional

from ..core import obs_hook
from .compiles import (annotate_compile, explain_compiles,
                       record_compile, reset_compiles)
from .export import (TelemetryExporter, get_exporter, install_exporter,
                     uninstall_exporter)
from .fleet import (FleetView, assemble_trace, collect_fleet_bundle,
                    fleet_prometheus_text, fleet_snapshot,
                    merged_chrome_trace, read_spool)
from .flight import (dump_flight, flight_recorder_path,
                     install_flight_recorder, uninstall_flight_recorder)
from .metrics import (build_info, dump_metrics, metrics_snapshot,
                      prometheus_text)
from .perf import (PerfObservatory, device_memory, disable_perf,
                   enable_perf, get_perf, perf_enabled, perf_report,
                   render_perf_report)
from .slo import (SLOMonitor, SLORule, get_slo_monitor,
                  install_slo_monitor, slo_status,
                  standard_serving_rules, uninstall_slo_monitor)
from .tracer import EVENT_KINDS, Tracer

__all__ = [
    "Tracer", "EVENT_KINDS", "enable", "disable", "enabled",
    "get_tracer", "emit", "span", "counter", "set_step",
    "record_compile", "explain_compiles", "reset_compiles",
    "annotate_compile",
    "prometheus_text", "metrics_snapshot", "dump_metrics", "build_info",
    "install_flight_recorder", "uninstall_flight_recorder",
    "dump_flight", "flight_recorder_path",
    "TelemetryExporter", "install_exporter", "uninstall_exporter",
    "get_exporter",
    "FleetView", "read_spool", "fleet_snapshot", "fleet_prometheus_text",
    "merged_chrome_trace", "assemble_trace", "collect_fleet_bundle",
    "PerfObservatory", "enable_perf", "disable_perf", "perf_enabled",
    "get_perf", "perf_report", "render_perf_report", "device_memory",
    "SLORule", "SLOMonitor", "install_slo_monitor",
    "uninstall_slo_monitor", "get_slo_monitor", "slo_status",
    "standard_serving_rules",
]


def enable(capacity: int = 8192, trace_ops: bool = True) -> Tracer:
    """Install (and return) a fresh process-wide tracer."""
    t = Tracer(capacity=capacity, trace_ops=trace_ops)
    obs_hook.set_tracer(t)
    return t


def disable() -> None:
    """Remove the tracer; instrumented sites return to the one
    None-check disabled path."""
    obs_hook.set_tracer(None)


def enabled() -> bool:
    return obs_hook.current() is not None


def get_tracer() -> Optional[Tracer]:
    return obs_hook.current()


def emit(kind: str, name: str, **args) -> None:
    """Emit one event on the active tracer; no-op when disabled."""
    t = obs_hook._tracer
    if t is not None:
        t.emit(kind, name, args=args or None)


def counter(name: str, delta=1, value=None) -> None:
    """Emit a counter-delta event; no-op when disabled."""
    t = obs_hook._tracer
    if t is not None:
        t.counter(name, delta, value=value)


def set_step(step: int) -> None:
    """Set the step correlation id on the active tracer (no-op when
    disabled)."""
    t = obs_hook._tracer
    if t is not None:
        t.set_step(step)


@contextlib.contextmanager
def span(name: str, **args):
    """Span context manager; a no-op (still yields) when disabled."""
    t = obs_hook._tracer
    if t is None:
        yield None
        return
    sid = t.begin_span(name, **args)
    try:
        yield sid
    finally:
        t.end_span(sid)
