"""Metrics export: monitor gauges + histograms as Prometheus text or JSON.

One exporter for every telemetry island: ``monitor.all_stats()`` /
``all_histograms()`` (which the serving engine, fs retry loop,
checkpoint store, fault injector and compile attribution all feed)
render as

- **Prometheus text exposition** (:func:`prometheus_text`) — gauges per
  stat, ``summary`` metrics per histogram (p50/p95/p99 quantile labels
  plus ``_sum``/``_count``), names sanitized to the Prometheus charset
  under a ``paddle_tpu_`` prefix.  ``serving/http.py`` serves this from
  ``/metrics`` when the scraper's Accept header asks for text.
- **JSON snapshots** (:func:`metrics_snapshot`) — the same registry as
  one timestamped dict, appendable as JSONL flight files from training
  via :func:`dump_metrics` (the ``hapi.callbacks.MetricsDump`` callback
  + ``FLAGS_metrics_dump_path``).
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, Optional

from ..core import flags, obs_hook
from ..utils import monitor

__all__ = ["prometheus_text", "metrics_snapshot", "dump_metrics",
           "build_info"]

_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "paddle_tpu_"

_build_info_cache: Optional[dict] = None


def build_info() -> dict:
    """Version/backend identity of this process — the fleet view diffs
    it across replicas to detect version skew (a hot-swapped weight
    snapshot landing on a replica running different jax/jaxlib is a
    real failure mode).  Cached after the first call; never initializes
    a backend the process has not already touched (device count falls
    back to 0 if jax has no initialized backend yet and counting would
    have to create one)."""
    global _build_info_cache
    if _build_info_cache is None:
        import jax
        import jaxlib
        from .. import __version__
        try:
            backend = jax.default_backend()
            devices = jax.device_count()
        except Exception:       # no usable backend: identity still dumps
            backend, devices = "unknown", 0
        _build_info_cache = {
            "framework": __version__,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": backend,
            "device_count": int(devices),
        }
    return dict(_build_info_cache)


def _prom_name(name: str) -> str:
    n = _PREFIX + _BAD.sub("_", name)
    return n


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _esc_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(extra_gauges: Optional[Dict[str, float]] = None
                    ) -> str:
    """The whole monitor registry (plus caller-supplied gauges) in
    Prometheus text exposition format (version 0.0.4).

    An ``extra_gauges`` key may carry a label set after the name —
    ``'serving_engine_queue_depth{engine="bert"}'`` — the name part is
    sanitized, the label part passes through verbatim (the serving
    front-end's per-engine labels ride this).  An extra gauge whose
    sanitized name matches a monitor-stat family joins that family
    (one ``# TYPE`` line, samples contiguous — strict parsers reject
    repeated or split families); an exact duplicate series (same name,
    same label set) is skipped, the registry's value wins."""
    t = obs_hook._tracer
    if t is not None:
        t.ring_stats()      # refresh the drop-accounting gauges
    stats = monitor.all_stats()
    hists = monitor.all_histograms()
    hist_names = {_prom_name(n) for n in hists}
    # family name -> (type, sample lines, label sets seen); insertion-
    # ordered so each family renders once, contiguously
    families: Dict[str, tuple] = {}

    def fam(m: str, typ: str) -> tuple:
        f = families.get(m)
        if f is None:
            f = families[m] = (typ, [], set())
        return f

    for name in sorted(stats):
        m = _prom_name(name)
        if m in hist_names:     # a stat and a histogram sharing a name
            m += "_stat"        # must not collide in the exposition
        _, smp, seen = fam(m, "gauge")
        smp.append(f"{m} {_fmt(stats[name])}")
        seen.add("")
    for name in sorted(hists):
        m = _prom_name(name)
        s = hists[name]
        _, smp, _ = fam(m, "summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            smp.append(f'{m}{{quantile="{q}"}} {_fmt(s[key])}')
        smp.append(f"{m}_sum {_fmt(s['sum'])}")
        smp.append(f"{m}_count {_fmt(int(s['count']))}")
    for name in sorted(extra_gauges or {}):
        base, brace, label = name.partition("{")
        m = _prom_name(base)
        if m in hist_names:
            m += "_stat"
        _, smp, seen = fam(m, "gauge")
        key = brace + label
        if key in seen:
            continue
        seen.add(key)
        smp.append(f"{m}{key} {_fmt(extra_gauges[name])}")
    bi = build_info()
    labels = ",".join(f'{k}="{_esc_label(v)}"'
                      for k, v in sorted(bi.items()))
    _, smp, _ = fam(_PREFIX + "build_info", "gauge")
    smp.append(f"{_PREFIX}build_info{{{labels}}} 1")
    lines = []
    for m, (typ, smp, _) in families.items():
        lines.append(f"# TYPE {m} {typ}")
        lines.extend(smp)
    return "\n".join(lines) + "\n"


def metrics_snapshot(extra: Optional[dict] = None) -> dict:
    """Timestamped JSON-ready snapshot of every stat and histogram,
    plus — when the respective layers are live — the tracer's drop
    accounting (``obs``), the current SLO evaluation (``slo``), and
    the perf observatory's drift report (``perf``), so one JSONL line
    is a complete offline-analysis record (latency distributions and
    objective state included, not just counters)."""
    t = obs_hook._tracer
    ring = t.ring_stats() if t is not None else None
    snap = {
        "time": time.time(),
        "stats": monitor.all_stats(),
        "histograms": monitor.all_histograms(),
        "build": build_info(),
    }
    if ring is not None:
        snap["obs"] = ring
    from . import slo as _slo
    if _slo.get_slo_monitor() is not None:
        snap["slo"] = _slo.slo_status(poll=False)
    p = obs_hook._perf
    if p is not None:
        snap["perf"] = p.report()
    if extra:
        snap.update(extra)
    return snap


def _rotate_dump(path: str) -> None:
    """Size-based rotation for the JSONL flight file: at/above
    ``FLAGS_metrics_dump_max_mb`` MiB, shift ``path.i`` -> ``path.i+1``
    (dropping the one past ``FLAGS_metrics_dump_keep``) and move the
    live file to ``path.1`` via atomic rename, so a long-lived replica
    never grows one unbounded file and a crash mid-rotation never loses
    the live file (rename is the last step)."""
    max_mb = float(flags.get_flag("metrics_dump_max_mb"))
    if max_mb <= 0:
        return
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size < max_mb * (1 << 20):
        return
    keep = max(1, int(flags.get_flag("metrics_dump_keep")))
    oldest = f"{path}.{keep}"
    if os.path.exists(oldest):
        os.remove(oldest)
    for i in range(keep - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    os.replace(path, f"{path}.1")


def dump_metrics(path: Optional[str] = None,
                 extra: Optional[dict] = None) -> str:
    """Append one :func:`metrics_snapshot` line to the JSONL flight
    file at ``path`` (default ``FLAGS_metrics_dump_path``); rotates the
    file first when ``FLAGS_metrics_dump_max_mb`` is set and the file
    has outgrown it."""
    path = path or flags.get_flag("metrics_dump_path")
    if not path:
        raise ValueError(
            "no metrics dump path: pass path= or set "
            "FLAGS_metrics_dump_path")
    _rotate_dump(path)
    with open(path, "a") as f:
        f.write(json.dumps(metrics_snapshot(extra)) + "\n")
    return path
