"""Fleet aggregator: one timeline / one metrics view across processes.

The consuming half of :mod:`.export`: supervisors and their children,
serving replicas and dataloader workers each spool checksummed
telemetry under ``FLAGS_obs_spool_dir/<role>-<pid>/``; this module
merges those spools (plus the calling process's live tracer) into

- :func:`fleet_snapshot` — every process's latest metrics snapshot and
  build identity keyed by ``<role>-<pid>``, with cross-process
  version-skew detection (``build_skew``);
- :func:`fleet_prometheus_text` — one Prometheus exposition where every
  family carries a ``{proc="<role>-<pid>"}`` label per process, family
  blocks contiguous (the PR-9 grammar contract);
- :func:`merged_chrome_trace` — one chrome-trace with a lane (pid) per
  process.  Lanes are aligned on the WALL clock: each process's
  ``Tracer.jsonable`` stamps every event with ``time`` (its own
  ``perf_counter``/``time.time`` anchor pair), and the merger rebases
  everything onto the earliest wall stamp.  Alignment is therefore as
  good as the hosts' clocks — on one machine (the supervisor tree)
  that is sub-millisecond; across machines it inherits NTP skew;
- :func:`assemble_trace` — the span tree of one distributed request:
  events carrying a trace id (adopted from ``X-Trace-Id`` by the HTTP
  plane, inherited by engine/registry/supervisor events) plus the
  rid/sid-correlated scheduler events they admit, with an end-to-end
  connectivity verdict;
- :func:`collect_fleet_bundle` — the fleet flight bundle: on a
  supervisor give-up or a registry incident, copy every child's black
  box (spool dirs, kill-time flight dumps) next to the parent's and
  write the merged views beside them, so the post-mortem starts from
  one directory.

:class:`FleetView` is the live counterpart for the registry control
plane: it aggregates per-replica readiness/SLO/inflight by scraping
registered replicas' ``/healthz`` + ``/metrics`` — ``GET /admin/fleet``
serves its :meth:`~FleetView.snapshot`.
"""
from __future__ import annotations

import glob
import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..core import flags, obs_hook
from .export import checksum_unwrap
from .metrics import _esc_label, _fmt, _prom_name, build_info

__all__ = ["read_spool", "fleet_snapshot", "fleet_prometheus_text",
           "merged_chrome_trace", "assemble_trace",
           "collect_fleet_bundle", "FleetView"]


# ---------------------------------------------------------------------------
# Spool reading
# ---------------------------------------------------------------------------

def _read_doc(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            return checksum_unwrap(f.read())
    except Exception:
        return None


def read_spool(spool_dir: Optional[str] = None) -> List[dict]:
    """Parse every per-process spool under ``spool_dir`` (default
    ``FLAGS_obs_spool_dir``).  Returns one record per process directory:
    ``{"label", "role", "pid", "dir", "meta", "metrics", "events",
    "segments", "corrupt"}`` — events deduped by id, sorted by wall
    time.  Corrupt documents (torn before ``write_atomic`` landed, or
    checksum-mismatched) are counted, never merged."""
    spool_dir = spool_dir or flags.get_flag("obs_spool_dir")
    procs: List[dict] = []
    if not spool_dir or not os.path.isdir(spool_dir):
        return procs
    for name in sorted(os.listdir(spool_dir)):
        d = os.path.join(spool_dir, name)
        if not os.path.isdir(d):
            continue
        if os.path.exists(os.path.join(d, "bundle.json")):
            continue        # an incident bundle parked in the spool dir
                            # is a copy of the fleet, not a process
        proc = {"label": name, "dir": d, "meta": None, "metrics": None,
                "events": [], "segments": 0, "corrupt": 0}
        meta = _read_doc(os.path.join(d, "meta.json"))
        if meta is not None:
            proc["meta"] = meta
        mdoc = _read_doc(os.path.join(d, "metrics.json"))
        if mdoc is not None:
            proc["metrics"] = mdoc.get("snapshot")
        elif os.path.exists(os.path.join(d, "metrics.json")):
            proc["corrupt"] += 1
        seen: set = set()
        for seg in sorted(glob.glob(os.path.join(d, "trace-*.json"))):
            body = _read_doc(seg)
            if body is None:
                proc["corrupt"] += 1
                continue
            proc["segments"] += 1
            for ev in body.get("events") or []:
                if ev.get("id") in seen:
                    continue        # hot-path tick raced the timer flush
                seen.add(ev.get("id"))
                proc["events"].append(ev)
        proc["events"].sort(key=lambda e: e.get("time", 0.0))
        role, _, pid = name.rpartition("-")
        if meta is not None:
            proc["role"] = meta.get("role", role or name)
            proc["pid"] = int(meta.get("pid", 0) or 0)
        else:
            proc["role"] = role or name
            proc["pid"] = int(pid) if pid.isdigit() else 0
        procs.append(proc)
    return procs


def _self_proc() -> Optional[dict]:
    """The calling process's live tracer as a spool-shaped record (the
    aggregating parent is part of the fleet too)."""
    trc = obs_hook._tracer
    if trc is None:
        return None
    role = flags.get_flag("obs_role") or "proc"
    from .metrics import metrics_snapshot
    return {"label": f"{role}-{os.getpid()}", "role": role,
            "pid": os.getpid(), "dir": None, "meta": None,
            "metrics": metrics_snapshot(),
            "events": [trc.jsonable(e) for e in trc.events()],
            "segments": 0, "corrupt": 0}


def _merge_self(procs: List[dict]) -> List[dict]:
    """Union the live tracer into the spool view: the self record's
    ring may hold events newer than the last flush, the spool may hold
    events the ring already evicted — merge by id, live last."""
    me = _self_proc()
    if me is None:
        return procs
    out = []
    merged = False
    for proc in procs:
        if proc.get("pid") == me["pid"]:
            seen = {e.get("id") for e in proc["events"]}
            proc = dict(proc, metrics=me["metrics"], events=(
                proc["events"] + [e for e in me["events"]
                                  if e.get("id") not in seen]))
            proc["events"].sort(key=lambda e: e.get("time", 0.0))
            merged = True
        out.append(proc)
    if not merged:
        out.append(me)
    return out


# ---------------------------------------------------------------------------
# Merged views
# ---------------------------------------------------------------------------

def fleet_snapshot(spool_dir: Optional[str] = None,
                   procs: Optional[Sequence[dict]] = None,
                   include_self: bool = True) -> dict:
    """One fleet-wide snapshot: per-process metrics + build identity,
    with build-skew detection (distinct build blocks across processes
    — a hot-swap fleet running mixed jax/jaxlib versions is flagged
    here before it becomes a weight-compatibility incident)."""
    if procs is None:
        procs = read_spool(spool_dir)
        if include_self:
            procs = _merge_self(list(procs))
    builds: Dict[str, List[str]] = {}
    out_procs = {}
    for proc in procs:
        meta = proc.get("meta") or {}
        snap = proc.get("metrics") or {}
        build = (meta.get("build") or snap.get("build")
                 or (build_info() if proc.get("dir") is None else None))
        if build:
            builds.setdefault(
                json.dumps(build, sort_keys=True), []).append(
                    proc["label"])
        out_procs[proc["label"]] = {
            "role": proc.get("role"),
            "pid": proc.get("pid"),
            "build": build,
            "metrics": snap,
            "events": len(proc.get("events") or ()),
            "segments": proc.get("segments", 0),
            "corrupt": proc.get("corrupt", 0),
        }
    return {
        "time": time.time(),
        "procs": out_procs,
        "build_skew": (sorted(builds.values(), key=len)
                       if len(builds) > 1 else []),
    }


def fleet_prometheus_text(spool_dir: Optional[str] = None,
                          procs: Optional[Sequence[dict]] = None,
                          include_self: bool = True) -> str:
    """Every process's stats/histograms as one Prometheus exposition,
    each sample labelled ``{proc="<role>-<pid>"}``.  Families render
    once, contiguously, with one ``# TYPE`` line (the same grammar
    contract :func:`..metrics.prometheus_text` keeps)."""
    if procs is None:
        procs = read_spool(spool_dir)
        if include_self:
            procs = _merge_self(list(procs))
    procs = [p for p in procs if p.get("metrics")]
    hist_names = set()
    for proc in procs:
        for n in (proc["metrics"].get("histograms") or {}):
            hist_names.add(_prom_name(n))
    families: Dict[str, tuple] = {}

    def fam(m: str, typ: str) -> tuple:
        f = families.get(m)
        if f is None:
            f = families[m] = (typ, [])
        return f

    stat_names = sorted({n for p in procs
                         for n in (p["metrics"].get("stats") or {})})
    for name in stat_names:
        m = _prom_name(name)
        if m in hist_names:
            m += "_stat"
        _, smp = fam(m, "gauge")
        for proc in procs:
            v = (proc["metrics"].get("stats") or {}).get(name)
            if v is None:
                continue
            smp.append(f'{m}{{proc="{_esc_label(proc["label"])}"}} '
                       f"{_fmt(v)}")
    h_names = sorted({n for p in procs
                      for n in (p["metrics"].get("histograms") or {})})
    for name in h_names:
        m = _prom_name(name)
        _, smp = fam(m, "summary")
        for proc in procs:
            s = (proc["metrics"].get("histograms") or {}).get(name)
            if s is None:
                continue
            pl = f'proc="{_esc_label(proc["label"])}"'
            for q, key in (("0.5", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                smp.append(f'{m}{{{pl},quantile="{q}"}} {_fmt(s[key])}')
            smp.append(f"{m}_sum{{{pl}}} {_fmt(s['sum'])}")
            smp.append(f"{m}_count{{{pl}}} {_fmt(int(s['count']))}")
    _, smp = fam(_prom_name("build_info"), "gauge")
    for proc in procs:
        build = ((proc.get("meta") or {}).get("build")
                 or (proc["metrics"] or {}).get("build"))
        if not build:
            continue
        labels = ",".join(
            [f'proc="{_esc_label(proc["label"])}"'] +
            [f'{k}="{_esc_label(v)}"' for k, v in sorted(build.items())])
        smp.append(f"{_prom_name('build_info')}{{{labels}}} 1")
    lines = []
    for m, (typ, smp) in families.items():
        if not smp:
            continue
        lines.append(f"# TYPE {m} {typ}")
        lines.extend(smp)
    return "\n".join(lines) + "\n"


def merged_chrome_trace(spool_dir: Optional[str] = None,
                        procs: Optional[Sequence[dict]] = None,
                        include_self: bool = True,
                        since_time: Optional[float] = None) -> dict:
    """One chrome-trace across the fleet: a lane (chrome ``pid``) per
    process, named by a ``process_name`` metadata event, every lane
    rebased onto the earliest wall stamp so parent/child timelines
    align.  ``since_time`` (unix seconds) keeps only events at/after
    it — the ``POST /admin/trace?secs=N`` capture window."""
    if procs is None:
        procs = read_spool(spool_dir)
        if include_self:
            procs = _merge_self(list(procs))
    lanes = []
    t0 = None
    for proc in procs:
        evs = [e for e in proc.get("events") or ()
               if e.get("time") is not None
               and (since_time is None or e["time"] >= since_time)]
        if not evs:
            continue
        lanes.append((proc, evs))
        first = evs[0].get("time")
        if t0 is None or first < t0:
            t0 = first
    out = []
    for proc, evs in lanes:
        pid = int(proc.get("pid") or 0)
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "ts": 0,
                    "args": {"name": str(proc["label"])}})
        for ev in evs:
            args = dict(ev.get("args") or {})
            if "step" in ev:
                args["step"] = ev["step"]
            if "parent" in ev:
                args["parent_span"] = ev["parent"]
            if "trace" in ev:
                args["trace"] = ev["trace"]
            if "remote_parent" in ev:
                args["remote_parent"] = ev["remote_parent"]
            args["proc"] = str(proc["label"])
            base = {
                "name": str(ev.get("name", "?")),
                "cat": str(ev.get("kind", "instant")),
                "pid": pid,
                "tid": int(ev.get("tid", 0)),
                "ts": max(0.0, (ev["time"] - t0) * 1e6),
            }
            if ev.get("kind") == "counter":
                val = args.get("value", args.get("delta", 0))
                out.append(dict(base, ph="C",
                                args={"value": float(val)}))
            elif "dur" in ev:
                out.append(dict(base, ph="X",
                                dur=float(ev["dur"]) * 1e6, args=args))
            else:
                out.append(dict(base, ph="i", s="t", args=args))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Distributed request assembly
# ---------------------------------------------------------------------------

def assemble_trace(procs: Sequence[dict], trace_id: str) -> dict:
    """The span tree of one distributed request across process lanes.

    Selection is two-phase: (1) every event stamped with ``trace_id``
    (the HTTP handler binds the adopted/minted id to its thread, so
    admission/enqueue events inherit it; generation schedulers stamp it
    into event args); (2) every event sharing a correlation id
    (``rid``/``sid``, singular or plural) with phase-1 events — the
    scheduler-thread dispatch/prefill/decode events that carry no
    thread-bound context.

    Connectivity is judged over the union of parent-span edges,
    cross-process ``remote_parent`` edges (the caller's ``X-Parent-
    Span``) and the correlation groups: ``connected`` means every
    selected event sits in ONE component — HTTP accept through
    admission, prefill, decode steps and finish hang together, even
    when the lanes come from different processes."""
    nodes: Dict[tuple, dict] = {}
    for proc in procs:
        pid = proc.get("pid", 0)
        for ev in proc.get("events") or ():
            args = ev.get("args") or {}
            if (ev.get("trace") == trace_id
                    or args.get("trace") == trace_id
                    or trace_id in (args.get("traces") or ())):
                nodes[(pid, ev.get("id"))] = ev
    # phase 2: pull in rid/sid-correlated scheduler events
    corr_ids = set()
    for ev in nodes.values():
        args = ev.get("args") or {}
        for k in ("rid", "sid"):
            if args.get(k) is not None:
                corr_ids.add((k, args[k]))
        for k, one in (("rids", "rid"), ("sids", "sid")):
            for v in args.get(k) or ():
                corr_ids.add((one, v))
    if corr_ids:
        for proc in procs:
            pid = proc.get("pid", 0)
            for ev in proc.get("events") or ():
                key = (pid, ev.get("id"))
                if key in nodes:
                    continue
                args = ev.get("args") or {}
                hit = any((k, args.get(k)) in corr_ids
                          for k in ("rid", "sid"))
                hit = hit or any(
                    (one, v) in corr_ids
                    for k, one in (("rids", "rid"), ("sids", "sid"))
                    for v in args.get(k) or ())
                if hit:
                    nodes[key] = ev
    # union-find connectivity
    parent = {k: k for k in nodes}

    def find(k):
        while parent[k] != k:
            parent[k] = parent[parent[k]]
            k = parent[k]
        return k

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    by_id: Dict[object, List[tuple]] = {}
    for (pid, eid) in nodes:
        by_id.setdefault(eid, []).append((pid, eid))
    groups: Dict[tuple, tuple] = {}
    for key, ev in nodes.items():
        pid = key[0]
        if "parent" in ev and (pid, ev["parent"]) in nodes:
            union(key, (pid, ev["parent"]))
        rp = ev.get("remote_parent")
        if rp is not None:
            try:
                rp = int(rp)
            except (TypeError, ValueError):
                rp = None
        if rp is not None:
            for other in by_id.get(rp, ()):
                if other[0] != pid:
                    union(key, other)
        args = ev.get("args") or {}
        pairs = [(k, args[k]) for k in ("rid", "sid")
                 if args.get(k) is not None]
        pairs += [(one, v)
                  for k, one in (("rids", "rid"), ("sids", "sid"))
                  for v in args.get(k) or ()]
        for pair in pairs:
            rep = groups.get(pair)
            if rep is None:
                groups[pair] = key
            else:
                union(key, rep)
        # same-trace events on one thread chain through the span tree
        # already; a same-trace event with NO resolvable link still
        # belongs to the request — tie it to the trace root group
        if ev.get("trace") == trace_id or args.get("trace") == trace_id:
            rep = groups.get(("__trace__", trace_id))
            if rep is None:
                groups[("__trace__", trace_id)] = key
            else:
                union(key, rep)
    components = len({find(k) for k in nodes})
    return {
        "trace": trace_id,
        "events": len(nodes),
        "pids": sorted({k[0] for k in nodes}),
        "names": sorted({str(ev.get("name")) for ev in nodes.values()}),
        "components": components,
        "connected": bool(nodes) and components == 1,
    }


# ---------------------------------------------------------------------------
# Fleet flight bundle
# ---------------------------------------------------------------------------

def collect_fleet_bundle(dest_dir: str,
                         spool_dir: Optional[str] = None,
                         extra_paths: Sequence[str] = (),
                         reason: str = "incident",
                         extra: Optional[dict] = None) -> str:
    """Collect every process's black box into ``dest_dir``: spool dirs
    copied verbatim, ``extra_paths`` (kill-time flight dumps, give-up
    dumps) copied beside them, plus the merged chrome-trace, fleet
    snapshot and a manifest.  The parent's own exporter is flushed
    first so its lane is current.  Supervisor give-up and registry
    incidents call this; it must never raise into the caller's
    failure path (best-effort per item, manifest records what
    landed)."""
    spool_dir = spool_dir or flags.get_flag("obs_spool_dir")
    os.makedirs(dest_dir, exist_ok=True)
    exp = obs_hook._export
    if exp is not None:
        exp.flush()
    manifest = {"reason": reason, "time": time.time(),
                "pid": os.getpid(), "spool_dir": spool_dir,
                "collected": [], "errors": []}
    if extra:
        manifest["extra"] = extra
    procs = read_spool(spool_dir)
    for proc in procs:
        try:
            shutil.copytree(proc["dir"],
                            os.path.join(dest_dir, proc["label"]),
                            dirs_exist_ok=True)
            manifest["collected"].append(proc["label"])
        except Exception as e:
            manifest["errors"].append(f"{proc['label']}: {e}")
    for p in extra_paths:
        try:
            if os.path.isfile(p):
                shutil.copy2(p, os.path.join(dest_dir,
                                             os.path.basename(p)))
                manifest["collected"].append(os.path.basename(p))
        except Exception as e:
            manifest["errors"].append(f"{p}: {e}")
    procs = _merge_self(list(procs))
    try:
        with open(os.path.join(dest_dir, "merged_trace.json"), "w") as f:
            json.dump(merged_chrome_trace(procs=procs), f)
    except Exception as e:
        manifest["errors"].append(f"merged_trace: {e}")
    try:
        with open(os.path.join(dest_dir, "fleet_snapshot.json"),
                  "w") as f:
            json.dump(fleet_snapshot(procs=procs), f, default=str)
    except Exception as e:
        manifest["errors"].append(f"fleet_snapshot: {e}")
    from ..utils import fs
    fs.write_atomic(os.path.join(dest_dir, "bundle.json"),
                    json.dumps(manifest, default=str).encode())
    return dest_dir


# ---------------------------------------------------------------------------
# Live registry-plane aggregation (GET /admin/fleet)
# ---------------------------------------------------------------------------

class FleetView:
    """Aggregated per-replica readiness/SLO/inflight for the control
    plane.  Register :class:`~paddle_tpu.serving.registry.ReplicaSet`s
    (their supervisors carry readiness and health URLs) or bare replica
    URLs; :meth:`snapshot` scrapes each replica's ``/healthz`` and
    ``/metrics`` (JSON) and returns the merged view ``GET /admin/fleet``
    serves.  Scrapes are best-effort with a short timeout — a dead
    replica reports ``reachable: false``, it never stalls the admin
    plane."""

    def __init__(self, timeout_s: float = 2.0):
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._targets: Dict[str, dict] = {}

    def register(self, name: str, replica_set=None,
                 urls: Sequence[str] = ()) -> None:
        with self._lock:
            self._targets[name] = {"replica_set": replica_set,
                                   "urls": list(urls)}

    def unregister(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)

    def _scrape(self, base_url: str) -> dict:
        import http.client
        from urllib.parse import urlparse
        u = urlparse(base_url)
        out: dict = {"reachable": False}
        conn = http.client.HTTPConnection(
            u.hostname or "127.0.0.1", u.port or 80,
            timeout=self.timeout_s)
        try:
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            body = json.loads(r.read() or b"{}")
            out["reachable"] = True
            out["ready"] = bool(r.status == 200)
            out["status"] = body.get("status")
            out["weights_version"] = body.get("weights_version")
            if "slo" in body:
                out["slo"] = body["slo"]
            conn.request("GET", "/metrics",
                         headers={"Accept": "application/json"})
            r = conn.getresponse()
            stats = json.loads(r.read() or b"{}")
            out["inflight"] = {}
            reg = stats.get("registry") or {}
            for k, v in (reg.get("inflight") or {}).items():
                out["inflight"][k] = v
            for key in ("queue_depth", "requests", "weights_version"):
                if isinstance(stats.get(key), (int, float)):
                    out.setdefault(key, stats[key])
            gen = stats.get("generation") or {}
            if gen:
                out["decode"] = {
                    k: gen[k] for k in ("active", "queue_depth", "state")
                    if k in gen}
        except (OSError, ValueError, http.client.HTTPException):
            pass
        finally:
            conn.close()
        return out

    def snapshot(self) -> dict:
        with self._lock:
            targets = {n: dict(t) for n, t in self._targets.items()}
        fleet = {}
        for name, target in targets.items():
            replicas: List[dict] = []
            rs = target.get("replica_set")
            if rs is not None:
                for info in rs.describe().get("replicas", ()):
                    entry = dict(info)
                    url = entry.get("url")
                    if url:
                        scraped = self._scrape(url)
                        # the supervisor's own readiness verdict wins
                        # over a scrape that raced a restart
                        scraped.update(
                            {k: v for k, v in entry.items()
                             if v is not None})
                        entry = scraped
                    replicas.append(entry)
            for url in target.get("urls") or ():
                replicas.append(dict({"url": url}, **self._scrape(url)))
            fleet[name] = {
                "replicas": replicas,
                "count": len(replicas),
                "ready": sum(1 for r in replicas if r.get("ready")),
            }
        out = {"time": time.time(), "fleet": fleet}
        spool = flags.get_flag("obs_spool_dir")
        if spool:
            snap = fleet_snapshot(spool, include_self=False)
            out["spool"] = {"procs": sorted(snap["procs"]),
                            "build_skew": snap["build_skew"]}
        return out
