"""paddle_tpu.testing — deterministic chaos tooling.

:mod:`fault` is the fault-injection framework: named injection points
(``fault.point("fs.open_write", path)``) compiled into the fs /
checkpoint / DataLoader / executor layers, armed by tests or by
``FLAGS_fault_spec`` with per-point probability, fire counts, and
exception classes.  Disarmed, a point is a single module-bool check —
production code pays nothing for carrying it.
"""
from . import fault  # noqa: F401
