"""Chaos smoke flow: preemption-safe training under injected faults.

Trains a tiny model twice — once fault-free, once under a canned chaos
spec (checkpoint-fs write flakes, one DataLoader worker hard-killed
mid-epoch, SIGTERM mid-training) — and reports failure unless the
faulted run *resumes to completion with bitwise-identical final
parameters*.  This is the executable proof that the recovery paths
(utils/fs retry loop, digest-verified checkpoint fallback/publish,
DataLoader worker respawn, TrainEpochRange preemption save) actually
compose into "preemptible pods can train" (ROADMAP north star;
reference: fluid/incubate/checkpoint + framework/io/fs.cc +
fluid/reader.py SIGCHLD handling).

Lives inside the package (not tools/) so forkserver DataLoader workers
can unpickle :class:`SmokeDataset` regardless of how the driver was
launched; ``tools/chaos_smoke.py`` is the CLI entry point and
``tests/test_fault_tolerance.py`` runs :func:`main` in-process.
"""
from __future__ import annotations

import os
import shutil
import signal
import sys
import tempfile

import numpy as np

# The canned chaos: two transient flakes on checkpoint writes (absorbed
# by the fs retry loop), and a DataLoader worker hard-killed when it
# picks up batch 1 (absorbed by respawn + re-enqueue; matching on the
# batch, not a worker id, is start-order independent).  SIGTERM is
# raised separately mid-epoch by _train below.
CHAOS_SPEC = ("fs.open_write:count=2,exc=TransientFSError;"
              "mp.worker_batch:count=1,action=exit,code=43,match=batch=1")

N, D, BATCH = 32, 4, 8


class SmokeDataset:
    """Deterministic regression data; module-level so forkserver
    DataLoader workers can unpickle it."""

    def __init__(self):
        rng = np.random.RandomState(7)
        self.x = rng.randn(N, D).astype(np.float32)
        self.y = (self.x @ rng.randn(D, 1).astype(np.float32))

    def __len__(self):
        return N

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _build():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    paddle.seed(1234)
    net = nn.Linear(D, 1)
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    return net, opt


def _train(ckpt_dir, epochs, num_workers=2, sigterm_after_epoch=None,
           verbose=False):
    """One training process: build fresh objects, auto-resume, run.
    Returns final weights, or None when SIGTERM ended the run early."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader
    from paddle_tpu.utils.checkpoint import TrainEpochRange

    net, opt = _build()
    loader = DataLoader(SmokeDataset(), batch_size=BATCH, shuffle=False,
                        num_workers=num_workers)
    r = TrainEpochRange(epochs, ckpt_dir, model=net, opt=opt)
    try:
        for epoch in r:
            for xb, yb in loader:
                loss = F.mse_loss(net(xb), yb)
                loss.backward()
                opt.step()
                opt.clear_grad()
            if verbose:
                print(f"  epoch {epoch}: loss={float(loss):.6f}")
            if sigterm_after_epoch is not None \
                    and epoch == sigterm_after_epoch:
                # the preemption notice arrives mid-training; the range
                # saves at this epoch boundary and exits cleanly
                os.kill(os.getpid(), signal.SIGTERM)
    except SystemExit as e:
        if e.code not in (0, None):
            raise
        assert r.preempted, "SystemExit without a preemption request"
        return None
    finally:
        pool = getattr(loader, "_mp_pool", None)
        if pool is not None:
            pool.close()
            loader._mp_pool = None
    return net.weight.numpy().copy(), net.bias.numpy().copy()


def main(epochs=4, verbose=False, workdir=None):
    import paddle_tpu as paddle
    from paddle_tpu.testing import fault
    from paddle_tpu.utils import fs, monitor

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    scheme = "chaossmoke"
    # checkpoint store: local dir mounted under a registered scheme with
    # the retry wrapper — the 'remote store with transient failures'
    # stand-in the fs flake targets
    fs.register_fs(scheme, fs.PrefixStripFS(fs.LocalFS(), scheme),
                   retry=True)
    old_backoff = paddle.get_flags("fs_retry_backoff_s")
    paddle.set_flags({"fs_retry_backoff_s": 0.01})
    try:
        if verbose:
            print("== reference run (fault-free) ==")
        ref = _train(f"{workdir}/ref_ckpt", epochs, verbose=verbose)
        assert ref is not None

        if verbose:
            print("== chaos run ==")
        chaos_dir = f"{scheme}://{workdir}/chaos_ckpt"
        monitor.stat_reset()
        fault.arm(CHAOS_SPEC, seed=0)
        try:
            out = _train(chaos_dir, epochs, verbose=verbose,
                         sigterm_after_epoch=1)
        finally:
            fault.disarm()
        if out is not None:
            print("FAIL: SIGTERM did not stop the first chaos run",
                  file=sys.stderr)
            return 1

        if verbose:
            print("== resume after preemption ==")
        out = _train(chaos_dir, epochs, verbose=verbose)
        if out is None:
            print("FAIL: resume run ended early", file=sys.stderr)
            return 1

        stats = monitor.all_stats()
        if verbose:
            print("recovery stats:", {k: v for k, v in sorted(
                stats.items()) if not k.startswith("fault.")})
        problems = []
        if stats.get("fs.retries", 0) < 2:
            problems.append(f"fs flake not retried "
                            f"(fs.retries={stats.get('fs.retries', 0)})")
        if stats.get("dataloader.worker_restarts", 0) < 1:
            problems.append("killed worker was not respawned")
        if stats.get("checkpoint.preempt_saves", 0) < 1:
            problems.append("SIGTERM did not trigger a boundary save")
        if not np.array_equal(out[0], ref[0]) \
                or not np.array_equal(out[1], ref[1]):
            problems.append(
                f"final params differ from fault-free run "
                f"(max |dW|={np.abs(out[0] - ref[0]).max():.3e})")
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("chaos_smoke OK: training survived fs flakes, a worker "
              "kill, and SIGTERM preemption with bitwise-identical "
              "final params")
        return 0
    finally:
        paddle.set_flags(old_backoff)
        fs._REGISTRY.pop(scheme, None)
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)
