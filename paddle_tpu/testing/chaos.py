"""Chaos smoke flows: training and serving under injected faults.

Trains a tiny model twice — once fault-free, once under a canned chaos
spec (checkpoint-fs write flakes, one DataLoader worker hard-killed
mid-epoch, SIGTERM mid-training) — and reports failure unless the
faulted run *resumes to completion with bitwise-identical final
parameters*.  This is the executable proof that the recovery paths
(utils/fs retry loop, digest-verified checkpoint fallback/publish,
DataLoader worker respawn, TrainEpochRange preemption save) actually
compose into "preemptible pods can train" (ROADMAP north star;
reference: fluid/incubate/checkpoint + framework/io/fs.cc +
fluid/reader.py SIGCHLD handling).

Lives inside the package (not tools/) so forkserver DataLoader workers
can unpickle :class:`SmokeDataset` regardless of how the driver was
launched; ``tools/chaos_smoke.py`` is the CLI entry point and
``tests/test_fault_tolerance.py`` runs :func:`main` in-process.

:func:`serving_main` is the serving-engine counterpart (ISSUE 4): under
injected dispatcher faults, queue-full shedding, and in-queue deadline
expiry, every *accepted* request must still get a bitwise-correct
response or a clean shed/deadline error — never a hang or a wrong
answer.  Bitwise is provable here because :func:`make_dyadic_model`
keeps every weight and input a small dyadic rational, so float
accumulation is exact in any batching/padding order.
"""
from __future__ import annotations

import os
import shutil
import signal
import sys
import tempfile

import numpy as np

# The canned chaos: two transient flakes on checkpoint writes (absorbed
# by the fs retry loop), and a DataLoader worker hard-killed when it
# picks up batch 1 (absorbed by respawn + re-enqueue; matching on the
# batch, not a worker id, is start-order independent).  SIGTERM is
# raised separately mid-epoch by _train below.
CHAOS_SPEC = ("fs.open_write:count=2,exc=TransientFSError;"
              "mp.worker_batch:count=1,action=exit,code=43,match=batch=1")

N, D, BATCH = 32, 4, 8


class SmokeDataset:
    """Deterministic regression data; module-level so forkserver
    DataLoader workers can unpickle it."""

    def __init__(self):
        rng = np.random.RandomState(7)
        self.x = rng.randn(N, D).astype(np.float32)
        self.y = (self.x @ rng.randn(D, 1).astype(np.float32))

    def __len__(self):
        return N

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _build():
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    paddle.seed(1234)
    net = nn.Linear(D, 1)
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    return net, opt


def _train(ckpt_dir, epochs, num_workers=2, sigterm_after_epoch=None,
           verbose=False):
    """One training process: build fresh objects, auto-resume, run.
    Returns final weights, or None when SIGTERM ended the run early."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader
    from paddle_tpu.utils.checkpoint import TrainEpochRange

    net, opt = _build()
    loader = DataLoader(SmokeDataset(), batch_size=BATCH, shuffle=False,
                        num_workers=num_workers)
    r = TrainEpochRange(epochs, ckpt_dir, model=net, opt=opt)
    try:
        for epoch in r:
            for xb, yb in loader:
                loss = F.mse_loss(net(xb), yb)
                loss.backward()
                opt.step()
                opt.clear_grad()
            if verbose:
                print(f"  epoch {epoch}: loss={float(loss):.6f}")
            if sigterm_after_epoch is not None \
                    and epoch == sigterm_after_epoch:
                # the preemption notice arrives mid-training; the range
                # saves at this epoch boundary and exits cleanly
                os.kill(os.getpid(), signal.SIGTERM)
    except SystemExit as e:
        if e.code not in (0, None):
            raise
        assert r.preempted, "SystemExit without a preemption request"
        return None
    finally:
        pool = getattr(loader, "_mp_pool", None)
        if pool is not None:
            pool.close()
            loader._mp_pool = None
    return net.weight.numpy().copy(), net.bias.numpy().copy()


def main(epochs=4, verbose=False, workdir=None):
    import json

    import paddle_tpu as paddle
    from paddle_tpu import observability
    from paddle_tpu.testing import fault
    from paddle_tpu.utils import fs, monitor

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_smoke_")
    scheme = "chaossmoke"
    # checkpoint store: local dir mounted under a registered scheme with
    # the retry wrapper — the 'remote store with transient failures'
    # stand-in the fs flake targets
    fs.register_fs(scheme, fs.PrefixStripFS(fs.LocalFS(), scheme),
                   retry=True)
    old_backoff = paddle.get_flags("fs_retry_backoff_s")
    paddle.set_flags({"fs_retry_backoff_s": 0.01})
    try:
        if verbose:
            print("== reference run (fault-free) ==")
        ref = _train(f"{workdir}/ref_ckpt", epochs, verbose=verbose)
        assert ref is not None

        if verbose:
            print("== chaos run ==")
        chaos_dir = f"{scheme}://{workdir}/chaos_ckpt"
        monitor.stat_reset()
        # black box: faulted runs must leave a readable flight record —
        # the SIGTERM preemption notice triggers the dump (the recorder
        # installs its handler first; the epoch range's chains to it)
        flight_path = os.path.join(workdir, "flight_record.json")
        observability.enable(capacity=4096)
        observability.install_flight_recorder(path=flight_path)
        fault.arm(CHAOS_SPEC, seed=0)
        try:
            out = _train(chaos_dir, epochs, verbose=verbose,
                         sigterm_after_epoch=1)
        finally:
            fault.disarm()
        if out is not None:
            print("FAIL: SIGTERM did not stop the first chaos run",
                  file=sys.stderr)
            return 1

        if verbose:
            print("== resume after preemption ==")
        out = _train(chaos_dir, epochs, verbose=verbose)
        if out is None:
            print("FAIL: resume run ended early", file=sys.stderr)
            return 1

        # the black box must exist and show what actually happened
        flight_problems = []
        if not os.path.exists(flight_path):
            flight_problems.append(
                "faulted run left no flight-recorder dump")
        else:
            with open(flight_path) as f:
                box = json.load(f)
            if box.get("reason") != "SIGTERM":
                flight_problems.append(
                    f"flight dump reason {box.get('reason')!r}, "
                    f"expected 'SIGTERM'")
            kinds = {e.get("kind") for e in box.get("events", [])}
            if "fault" not in kinds:
                flight_problems.append(
                    "flight dump lacks the injected fault event")
            if "checkpoint" not in kinds:
                flight_problems.append(
                    "flight dump lacks checkpoint events")

        stats = monitor.all_stats()
        if verbose:
            print("recovery stats:", {k: v for k, v in sorted(
                stats.items()) if not k.startswith("fault.")})
        problems = list(flight_problems)
        if stats.get("fs.retries", 0) < 2:
            problems.append(f"fs flake not retried "
                            f"(fs.retries={stats.get('fs.retries', 0)})")
        if stats.get("dataloader.worker_restarts", 0) < 1:
            problems.append("killed worker was not respawned")
        if stats.get("checkpoint.preempt_saves", 0) < 1:
            problems.append("SIGTERM did not trigger a boundary save")
        if not np.array_equal(out[0], ref[0]) \
                or not np.array_equal(out[1], ref[1]):
            problems.append(
                f"final params differ from fault-free run "
                f"(max |dW|={np.abs(out[0] - ref[0]).max():.3e})")
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("chaos_smoke OK: training survived fs flakes, a worker "
              "kill, and SIGTERM preemption with bitwise-identical "
              "final params (+ a readable flight-recorder black box)")
        return 0
    finally:
        observability.uninstall_flight_recorder()
        observability.disable()
        paddle.set_flags(old_backoff)
        fs._REGISTRY.pop(scheme, None)
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Serving chaos (ISSUE 4): dispatcher flakes + shedding + deadlines
# ---------------------------------------------------------------------------

# Dispatcher flakes: 3 random fires across the run, seeded for replay.
# The engine retries a flaked batch (inference is pure), and with
# dispatch_retries=3 a rule capped at count=3 can NEVER exhaust a
# batch's 4 attempts — so every accepted request must come back correct.
SERVING_CHAOS_SPEC = "serving.dispatch:p=0.3,count=3"


def make_dyadic_model(in_dim=8, hidden=16, out_dim=4):
    """A tiny MLP whose weights are small dyadic rationals (k/8).

    With inputs that are also dyadic (k/4), every product and partial
    sum is exactly representable in float32, so outputs are bitwise
    identical regardless of batch coalescing, padding, or reduction
    order — the property the serving chaos/smoke gates assert."""
    import numpy as np

    from paddle_tpu import nn

    model = nn.Sequential(nn.Linear(in_dim, hidden), nn.ReLU(),
                          nn.Linear(hidden, out_dim))
    for p in model.parameters():
        p.set_value(np.round(p.numpy() * 8.0) / 8.0)
    return model


def serving_main(requests=40, clients=4, verbose=False):
    """Serving chaos gate; returns 0 on success, 1 on failure."""
    import tempfile
    import threading
    import time

    import paddle_tpu as paddle
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.testing import fault
    from paddle_tpu.utils import monitor

    paddle.seed(5)
    model = make_dyadic_model()
    prefix = os.path.join(tempfile.mkdtemp(prefix="serve_chaos_"), "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))

    rng = np.random.RandomState(17)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 5), 8)) / 4.0)
            .astype(np.float32) for _ in range(requests)]
    refs = [np.asarray(pred.run([x])[0]) for x in reqs]

    max_queue = 8
    engine = serving.InferenceEngine(pred, max_batch_size=8,
                                     batch_timeout_ms=5.0,
                                     max_queue=max_queue,
                                     dispatch_retries=3)
    engine.warmup()

    problems = []
    monitor.stat_reset()
    fault.arm(SERVING_CHAOS_SPEC, seed=1)
    try:
        # -- concurrent traffic under dispatcher flakes ------------------
        outcomes = [None] * requests

        def client(idx):
            for i in range(idx, requests, clients):
                try:
                    outcomes[i] = engine.infer_sync(
                        [reqs[i]], timeout=30)
                except Exception as e:  # noqa: BLE001 - gated below
                    outcomes[i] = e

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for i, (out, ref) in enumerate(zip(outcomes, refs)):
            if isinstance(out, Exception):
                problems.append(
                    f"accepted request {i} failed under chaos: "
                    f"{type(out).__name__}: {out}")
            elif out is None:
                problems.append(f"request {i} hung (no outcome)")
            elif not np.array_equal(out[0], ref):
                problems.append(
                    f"request {i}: WRONG ANSWER under chaos (max "
                    f"|d|={np.abs(out[0] - ref).max():.3e})")

        # -- deterministic queue-full shedding ---------------------------
        engine.pause()
        burst = []
        for i in range(max_queue + 4):
            try:
                burst.append(engine.infer([reqs[i % requests]]))
            except serving.QueueFull:
                burst.append("shed")
        n_shed = sum(1 for b in burst if b == "shed")
        if n_shed != 4:
            problems.append(f"expected exactly 4 sheds from a "
                            f"{max_queue + 4}-burst into a paused "
                            f"{max_queue}-queue, got {n_shed}")

        engine.resume()
        accepted = [b for b in burst if b != "shed"]
        for i, f in enumerate(accepted):
            try:
                f.result(timeout=30)
            except Exception as e:  # noqa: BLE001
                problems.append(f"post-pause request {i} failed: "
                                f"{type(e).__name__}: {e}")

        # -- in-queue deadline expiry (never occupies a batch slot) ------
        engine.pause()          # idle queue now: the probe is admitted
        doomed = engine.infer([reqs[0]], deadline_ms=1.0)
        time.sleep(0.02)
        engine.resume()
        try:
            doomed.result(timeout=30)
            problems.append("1 ms deadline request was served instead "
                            "of expiring in-queue")
        except serving.DeadlineExceeded:
            pass
        except Exception as e:  # noqa: BLE001
            problems.append(f"deadline request died oddly: "
                            f"{type(e).__name__}: {e}")
    finally:
        fault.disarm()
    engine.drain(timeout=30)
    stats = engine.stats()
    engine.close()

    fired = monitor.get_stat("fault.fired.serving.dispatch")
    if fired < 1:
        problems.append("chaos spec never fired a dispatcher fault "
                        "(nothing was actually tested)")
    if stats["counters"]["dispatch_retries"] < fired:
        problems.append(
            f"dispatcher fired {fired} faults but only "
            f"{stats['counters']['dispatch_retries']} retries ran")
    if stats["recompiles_after_warmup"] != 0:
        problems.append(f"hot path recompiled "
                        f"{stats['recompiles_after_warmup']}x under chaos")
    if verbose:
        print(f"serving chaos stats: faults={fired} "
              f"retries={stats['counters']['dispatch_retries']} "
              f"shed={stats['counters']['shed']} "
              f"expired={stats['counters']['deadline_expired']} "
              f"batches={stats['counters']['batches']}")
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("serving chaos OK: dispatcher flakes retried, queue-full "
          "shed cleanly, deadlines expired in-queue, every served "
          "response bitwise-correct")
    return 0


# ---------------------------------------------------------------------------
# Generation chaos (ISSUE 7): decode flakes + mid-generation deadlines
# ---------------------------------------------------------------------------

# Decode-step flakes: the scheduler retries a flaked step (the step is
# functional over the KV pool, and injected faults fire before
# dispatch), and with decode_retries=3 a rule capped at count=3 can
# never exhaust a step's 4 attempts — every admitted sequence must
# stream to a clean finish.
GENERATION_CHAOS_SPEC = "serving.decode_step:p=0.3,count=3"


def make_dyadic_lm(**kw):
    """A tiny PagedDecoderLM with k/64 dyadic weights (see
    make_dyadic_model): per-row decode math reproduces bitwise in any
    slot/batch/page placement, which is what makes the admission-order
    parity gate below exact instead of tolerance-based."""
    from paddle_tpu.serving import PagedDecoderLM

    kw.setdefault("vocab_size", 32)
    kw.setdefault("hidden", 16)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("seed", 3)
    return PagedDecoderLM(dyadic=True, **kw)


def generation_main(requests=18, clients=3, verbose=False):
    """Generative serving chaos gate; returns 0 on success, 1 on failure.

    Asserts, under injected decode flakes:
      * every admitted sequence streams to a clean finish with tokens
        BITWISE-identical to a fault-free serial run in a different
        admission order (continuous batching must not change results);
      * a mid-generation deadline expiry evicts its sequence with
        DeadlineExceeded after streaming some tokens;
      * page-pool accounting returns to zero (no leaked pages) and the
        decode hot path never recompiles.
    """
    import threading
    import time

    from paddle_tpu import serving
    from paddle_tpu.testing import fault
    from paddle_tpu.utils import monitor

    model = make_dyadic_lm()
    mk_engine = lambda: serving.GenerationEngine(  # noqa: E731
        model, num_slots=4, page_size=4, max_context=64,
        max_queue=4 * requests, decode_retries=3)

    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, 32, rng.randint(1, 9)).tolist()
               for _ in range(requests)]
    budgets = [int(rng.randint(3, 9)) for _ in range(requests)]

    problems = []
    monitor.stat_reset()
    engine = mk_engine()
    engine.warmup()
    fault.arm(GENERATION_CHAOS_SPEC, seed=1)
    try:
        # -- concurrent ragged traffic under decode flakes ---------------
        outcomes = [None] * requests

        def client(idx):
            for i in range(idx, requests, clients):
                try:
                    got = []
                    stream = engine.generate(prompts[i],
                                             max_new_tokens=budgets[i],
                                             temperature=0.7, seed=i)
                    for tok in stream.tokens(timeout=60):
                        got.append(tok)      # exercise streaming
                    if got != stream.result(0):
                        raise AssertionError(
                            "streamed tokens != final result")
                    outcomes[i] = got
                except Exception as e:  # noqa: BLE001 - gated below
                    outcomes[i] = e

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for i, out in enumerate(outcomes):
            if isinstance(out, Exception):
                problems.append(
                    f"admitted sequence {i} failed under chaos: "
                    f"{type(out).__name__}: {out}")
            elif out is None or len(out) != budgets[i]:
                problems.append(
                    f"sequence {i}: {0 if out is None else len(out)} "
                    f"tokens, budget {budgets[i]}")
    finally:
        fault.disarm()

    # -- admission-order parity: serial fault-free run, reversed order --
    ref_engine = mk_engine()
    ref_engine.warmup()
    refs = [None] * requests
    for i in reversed(range(requests)):
        refs[i] = ref_engine.generate_sync(
            prompts[i], timeout=60, max_new_tokens=budgets[i],
            temperature=0.7, seed=i)
    for i, (out, ref) in enumerate(zip(outcomes, refs)):
        if isinstance(out, list) and out != ref:
            problems.append(
                f"sequence {i}: tokens differ from serial run "
                f"(admission order leaked into results): {out} != {ref}")
    ref_engine.close()

    # -- mid-generation deadline expiry (deterministic via pause; the
    # deadline is generous so even a loaded runner streams two tokens
    # before the pause lets it lapse) ------------------------------------
    doomed = engine.generate(prompts[0], max_new_tokens=40,
                             deadline_ms=2000.0)
    it = doomed.tokens(timeout=30)
    first = []
    try:
        first.append(next(it))          # decoding has demonstrably begun
        first.append(next(it))
        engine.pause()
        time.sleep(2.2)                 # deadline lapses mid-generation
        engine.resume()
        for _ in it:
            pass
        problems.append("mid-generation deadline did not expire")
    except serving.DeadlineExceeded:
        if len(first) < 2:
            problems.append("deadline expired before decoding began "
                            "(not a MID-generation expiry)")
    except Exception as e:  # noqa: BLE001
        problems.append(f"deadline sequence died oddly: "
                        f"{type(e).__name__}: {e}")
    finally:
        engine.resume()                 # never leave the engine paused

    engine.drain(timeout=60)
    stats = engine.stats()
    engine.close()

    fired = monitor.get_stat("fault.fired.serving.decode_step")
    if fired < 1:
        problems.append("chaos spec never fired a decode fault "
                        "(nothing was actually tested)")
    if stats["counters"]["decode_retries"] < fired:
        problems.append(
            f"decode fired {fired} faults but only "
            f"{stats['counters']['decode_retries']} retries ran")
    if stats["recompiles_after_warmup"] != 0:
        problems.append(f"decode hot path recompiled "
                        f"{stats['recompiles_after_warmup']}x under chaos")
    if stats["page_pool"]["in_use"] != 0:
        problems.append(f"page pool leaked "
                        f"{stats['page_pool']['in_use']} pages")
    if stats["counters"]["pages_allocated"] \
            != stats["counters"]["pages_freed"]:
        problems.append(
            f"page accounting: {stats['counters']['pages_allocated']} "
            f"allocated vs {stats['counters']['pages_freed']} freed")
    if verbose:
        print(f"generation chaos stats: faults={fired} "
              f"retries={stats['counters']['decode_retries']} "
              f"expired={stats['counters']['deadline_expired']} "
              f"steps={stats['counters']['decode_steps']} "
              f"occupancy={stats['mean_slot_occupancy']:.2f}")
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("generation chaos OK: decode flakes retried, tokens bitwise-"
          "identical to serial admission, mid-generation deadline "
          "evicted cleanly, page pool fully reclaimed")
    return 0


# ---------------------------------------------------------------------------
# Reshard chaos (ISSUE 8): kill mid-run, restore onto a DIFFERENT mesh
# ---------------------------------------------------------------------------

def _reshard_feed():
    """The deterministic regression feed every mesh-drill incarnation
    (reference runs, chaos runs, supervised children — whatever the
    process) must reconstruct identically, or the loss-parity gates
    compare divergent trajectories."""
    import numpy as np

    rng = np.random.RandomState(7)
    xs = rng.standard_normal((64, D)).astype(np.float32)
    ys = xs @ rng.standard_normal((D, 1)).astype(np.float32)
    return {"x": xs, "y": ys}


def _reshard_build(lr=0.05):
    """One fleet-sharded static training program (the 'unchanged user
    code' both mesh sizes run)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import distributed as dist, optimizer

    paddle.seed(1234)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, D], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        pred = paddle.static.nn.fc(x, 8)
        pred = paddle.static.nn.fc(F.relu(pred), 1)
        loss = F.mse_loss(pred, y)
        f = dist.fleet
        f.init(is_collective=True, strategy=dist.DistributedStrategy())
        opt = f.distributed_optimizer(optimizer.Adam(learning_rate=lr))
        opt.minimize(loss)
    return main, loss, paddle.static.Executor()


def reshard_main(steps=12, save_every=4, kill_after=6, verbose=False,
                 workdir=None):
    """Mid-run mesh-size change via sharded checkpoint restore.

    Reference run: the training program on mesh ``{dp: 8}``,
    uninterrupted, recording the per-step loss trajectory.  Chaos run:
    same program, sharded SnapshotStore saves every ``save_every``
    steps, a fault injected at ``executor.run`` kills step
    ``kill_after`` — then the program is REBUILT on mesh ``{dp: 2}``,
    restored from the (digest-verified, per-shard) snapshot, resharded
    onto the smaller mesh, and trained to completion.  Gates:

    - the restore itself is bitwise (gathered params == params at the
      save point on the old mesh);
    - the post-restore loss trajectory matches the uninterrupted run's
      same steps (rtol 1e-5 — reduction order differs across dp
      degrees);
    - the injected kill actually fired (the run was really interrupted).
    """
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.testing import fault
    from paddle_tpu.utils.checkpoint import SnapshotStore

    import jax
    if len(jax.devices()) < 8:
        print("FAIL: reshard scenario needs 8 devices "
              "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)
        return 1

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_reshard_")
    feed = _reshard_feed()

    was_static = paddle.in_static_mode() \
        if hasattr(paddle, "in_static_mode") else False
    paddle.enable_static()
    try:
        # -- reference: uninterrupted on mesh {dp: 8} ----------------------
        init_mesh({"dp": 8})
        main, loss, exe = _reshard_build()
        init_mesh({"dp": 8})
        ref_losses = [float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0])
                      for _ in range(steps)]
        exe.close()
        paddle.static.reset_default_programs()
        if verbose:
            print(f"reference (mesh dp=8): {ref_losses}")

        # -- chaos: save every N, injected kill, reshard to {dp: 2} --------
        store = SnapshotStore(f"{workdir}/ckpt")
        init_mesh({"dp": 8})
        main, loss, exe = _reshard_build()
        init_mesh({"dp": 8})
        saved_at = -1
        saved_params = None
        killed = False
        fault.arm(f"executor.run:count=1,after={kill_after}")
        try:
            for step in range(steps):
                try:
                    exe.run(main, feed=feed, fetch_list=[loss])
                except fault.FaultInjected:
                    killed = True
                    break
                if (step + 1) % save_every == 0:
                    store.save(step, {"train": exe.sharded_state(main)})
                    saved_at = step
                    saved_params = {
                        k: np.asarray(v).copy() for k, v in
                        exe.sharded_state(main)._getter()
                        ["params"].items()}
        finally:
            fault.disarm()
        exe.close()
        paddle.static.reset_default_programs()
        if not killed:
            print("FAIL: injected executor.run fault never fired",
                  file=sys.stderr)
            return 1
        if saved_at < 0:
            print("FAIL: kill arrived before the first snapshot "
                  "(raise kill_after or lower save_every)",
                  file=sys.stderr)
            return 1
        if verbose:
            print(f"killed at step {kill_after}, last snapshot at "
                  f"step {saved_at}")

        init_mesh({"dp": 2})  # the replacement pod is a different size
        main2, loss2, exe2 = _reshard_build()
        init_mesh({"dp": 2})
        ss = exe2.sharded_state(main2)
        store.restore({"train": ss})
        restored = {k: np.asarray(v) for k, v in
                    ss._getter()["params"].items()}
        problems = []
        for k in saved_params:
            if not np.array_equal(restored[k], saved_params[k]):
                problems.append(
                    f"restored param {k} not bitwise-identical across "
                    f"the mesh-8 -> mesh-2 reshard")
        cont = [float(exe2.run(main2, feed=feed,
                               fetch_list=[loss2])[0])
                for _ in range(steps - saved_at - 1)]
        exe2.close()
        paddle.static.reset_default_programs()
        if verbose:
            print(f"resumed (mesh dp=2):  {cont}")

        expect = ref_losses[saved_at + 1:]
        try:
            np.testing.assert_allclose(cont, expect, rtol=1e-5)
        except AssertionError as e:
            problems.append(
                f"post-restore loss trajectory diverged from the "
                f"uninterrupted run: {e}")
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("chaos reshard OK: killed mid-run on mesh dp=8, restored "
              f"the step-{saved_at} sharded snapshot onto mesh dp=2 "
              "(bitwise params), loss trajectory matches the "
              "uninterrupted run")
        return 0
    finally:
        if not was_static:
            paddle.disable_static()
        import paddle_tpu.static as _st
        _st.reset_default_programs()
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Hot-swap chaos (ISSUE 18): digest-verified weight swaps under concurrent
# traffic, one corrupted snapshot, one supervisor-restarted replica crash
# ---------------------------------------------------------------------------

def _scaled_artifact(scale, workdir, tag):
    """``jit.save`` the dyadic inference model with every weight scaled
    by ``scale``.  Power-of-two scales keep every value exactly
    representable, so each published version has its own bitwise-exact
    reference outputs — which is what lets the swap gate attribute
    every served response to exactly one weights version."""
    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.jit import InputSpec

    paddle.seed(5)
    model = make_dyadic_model()
    for p in model.parameters():
        p.set_value(p.numpy() * scale)
    prefix = os.path.join(workdir, f"m_{tag}")
    jit.save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
    return prefix


def _swap_serving_entry(prefix, port, state_file, stop_file):
    """Supervised serving replica (module-level so spawn can pickle it).

    Binds the HTTP plane not-ready, warms the batch buckets, marks
    ready, then serves until ``stop_file`` appears.  The FIRST
    incarnation hard-crashes (``os._exit``) about a second after going
    ready — with the parent's clients mid-request — so the supervisor
    must restart it and the replacement must re-warm and go ready
    again before traffic recovers."""
    import threading
    import time

    from paddle_tpu import inference, serving

    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=8,
                                     batch_timeout_ms=5.0)
    srv = serving.ServingServer(engine, port=port, ready=False).start()
    engine.warmup()
    srv.mark_ready()
    if not os.path.exists(state_file):
        with open(state_file, "w") as f:
            f.write("1")

        def _die():
            time.sleep(1.0)
            os._exit(9)         # a hard replica crash, mid-traffic

        threading.Thread(target=_die, daemon=True).start()
    while not os.path.exists(stop_file):
        time.sleep(0.05)
    srv.close()
    engine.drain(timeout=10.0)
    engine.close()


def swap_main(requests=16, clients=3, verbose=False, workdir=None,
              supervised=True):
    """Swap-under-fire gate; returns 0 on success, 1 on failure.

    Part one (in-process, engines under concurrent traffic): a
    :class:`~paddle_tpu.serving.hotswap.WeightWatcher` applies three
    live weight swaps (versions 1..3) to an InferenceEngine AND a
    GenerationEngine while client threads hammer both, then one
    deliberately corrupted snapshot (version 4) must be rejected with
    the engines still serving version 3.  Gates: every response is
    bitwise-correct for *some* published version (inference batches
    run under exactly one predictor, so no response may mix versions;
    generation sequences that demonstrably ran inside one version must
    match that version's serial reference), each applied version is
    bitwise-verified by a settled serial pass, ``/healthz`` readiness
    stays green through every applied swap, zero hot-path recompiles,
    zero stranded futures, and the page pool is fully reclaimed.

    Part two (``supervised=True``): a :class:`ServingSupervisor`
    replica crashes hard mid-traffic; the supervisor restarts it, the
    replacement re-warms and goes ready, clients ride through via the
    reconnect path (``client.reconnects``), and post-restart responses
    are again bitwise-correct.
    """
    import threading
    import time

    from paddle_tpu import inference, serving
    from paddle_tpu.serving.hotswap import (PARAMS_PAYLOAD, WeightWatcher,
                                            publish_weights)
    from paddle_tpu.utils import monitor
    from paddle_tpu.utils.checkpoint import SnapshotStore

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_swap_")
    problems = []
    monitor.stat_reset()
    scales = {0: 1.0, 1: 0.5, 2: 0.25, 3: 2.0}

    # -- per-version bitwise references -----------------------------------
    prefixes = {v: _scaled_artifact(s, workdir, f"v{v}")
                for v, s in scales.items()}
    preds = {v: inference.create_predictor(inference.Config(prefixes[v]))
             for v in scales}
    rng = np.random.RandomState(17)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 5), 8)) / 4.0)
            .astype(np.float32) for _ in range(requests)]
    inf_refs = {v: [np.asarray(preds[v].run([x])[0]) for x in reqs]
                for v in scales}
    for v in (1, 2, 3):
        if all(np.array_equal(a, b)
               for a, b in zip(inf_refs[v], inf_refs[0])):
            problems.append(f"version {v} artifact is output-identical "
                            f"to version 0 (swap would be unobservable)")

    base_params = {k: np.asarray(v).copy()
                   for k, v in make_dyadic_lm().params.items()}
    params_for = {v: {k: a * s for k, a in base_params.items()}
                  for v, s in scales.items()}
    prompts = [rng.randint(0, 32, rng.randint(1, 9)).tolist()
               for _ in range(6)]
    budgets = [int(rng.randint(3, 7)) for _ in prompts]

    # generation references: ONE warmed engine, serially hot-swapped
    # through the version sequence (idle swaps — also a deterministic
    # exercise of the staged-commit path itself)
    ref_gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                       page_size=4, max_context=64,
                                       max_queue=64)
    ref_gen.warmup()
    gen_refs = {}
    for v in sorted(scales):
        if v:
            ref_gen.swap_weights(params_for[v], v)
        gen_refs[v] = [ref_gen.generate_sync(
            prompts[i], timeout=60, max_new_tokens=budgets[i],
            temperature=0.7, seed=i) for i in range(len(prompts))]
    ref_stats = ref_gen.stats()
    ref_gen.close()
    if ref_stats["counters"]["weight_swaps"] != 3 \
            or ref_stats["recompiles_after_warmup"] != 0:
        problems.append(
            f"reference engine: {ref_stats['counters']['weight_swaps']} "
            f"swaps, {ref_stats['recompiles_after_warmup']} recompiles "
            f"(expected 3 swaps, 0 recompiles)")

    # -- part one: live engines, watcher, fire ------------------------------
    engine = serving.InferenceEngine(preds[0], max_batch_size=8,
                                     batch_timeout_ms=5.0,
                                     max_queue=8 * requests)
    engine.warmup()
    gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                   page_size=4, max_context=64,
                                   max_queue=256)
    gen.warmup()
    srv = serving.ServingServer(engine, generation=gen, port=0).start()
    store = SnapshotStore(os.path.join(workdir, "weights"))
    watcher = WeightWatcher(store, engine=engine, generation=gen,
                            poll_s=0.05).start()

    stop = threading.Event()
    ready_bad, versions_seen, probes = [], set(), [0]
    inf_outcomes, gen_outcomes = [], []

    def prober():
        c = serving.Client(srv.url)
        while not stop.is_set():
            h = c.healthz()
            probes[0] += 1
            if not h.get("ready") or h.get("status") != "running":
                ready_bad.append(dict(h))
            versions_seen.add(int(h.get("weights_version", -1)))
            time.sleep(0.01)

    def inf_client(idx):
        k = idx
        while not stop.is_set():
            i = k % len(reqs)
            k += clients
            try:
                out = engine.infer_sync([reqs[i]], timeout=30)
                inf_outcomes.append((i, np.asarray(out[0])))
            except Exception as e:  # noqa: BLE001 - gated below
                inf_outcomes.append((i, e))

    def gen_client(idx):
        k = idx
        while not stop.is_set():
            i = k % len(prompts)
            k += clients
            v_before = gen.weights_version
            try:
                toks = gen.generate_sync(
                    prompts[i], timeout=60, max_new_tokens=budgets[i],
                    temperature=0.7, seed=i)
                gen_outcomes.append((i, v_before, gen.weights_version,
                                     toks))
            except Exception as e:  # noqa: BLE001 - gated below
                gen_outcomes.append((i, v_before, -1, e))

    threads = [threading.Thread(target=prober, daemon=True)]
    threads += [threading.Thread(target=inf_client, args=(c,),
                                 daemon=True) for c in range(clients)]
    threads += [threading.Thread(target=gen_client, args=(c,),
                                 daemon=True) for c in range(clients)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.4)                 # traffic lands on version 0
        for v in (1, 2, 3):
            publish_weights(store, v, artifact_prefix=prefixes[v],
                            params=params_for[v])
            deadline = time.monotonic() + 60
            while watcher.version < v \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            if watcher.version != v:
                problems.append(
                    f"swap to version {v} not applied within 60s "
                    f"(last_error={watcher.last_error})")
                break
            # settled serial pass: the freshly applied version must
            # answer bitwise-correctly under its OWN references while
            # the fire traffic keeps coalescing around these requests
            for i in range(3):
                out = engine.infer_sync([reqs[i]], timeout=30)
                if not np.array_equal(out[0], inf_refs[v][i]):
                    problems.append(
                        f"version {v}: settled inference response {i} "
                        f"not bitwise (max |d|="
                        f"{np.abs(out[0] - inf_refs[v][i]).max():.3e})")
            toks = gen.generate_sync(prompts[0], timeout=60,
                                     max_new_tokens=budgets[0],
                                     temperature=0.7, seed=0)
            if toks != gen_refs[v][0]:
                problems.append(f"version {v}: settled generation not "
                                f"bitwise ({toks} != {gen_refs[v][0]})")
            if verbose:
                print(f"swap v{v} applied "
                      f"(engine={engine.weights_version} "
                      f"gen={gen.weights_version})")
            time.sleep(0.4)             # fire window on this version

        # -- the corrupted snapshot: rejected, never applied -------------
        # (stop the poller first so the byte flip is atomic w.r.t. the
        # watcher — a real corruption races the same way: the digest
        # check, not timing, is the defense)
        watcher.stop()
        publish_weights(store, 4, artifact_prefix=prefixes[3],
                        params=params_for[3])
        snap = store.latest_snapshot()
        path = os.path.join(store.dir, snap["dir"],
                            f"{PARAMS_PAYLOAD}.pdparams")
        with open(path, "r+b") as f:
            f.seek(20)
            b = f.read(1)
            f.seek(20)
            f.write(bytes([b[0] ^ 0xFF]))
        import warnings as _warnings
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            got = watcher.check_once()
        if got is not None or watcher.last_rejected != 4:
            problems.append(
                f"corrupted snapshot not rejected (applied={got}, "
                f"last_rejected={watcher.last_rejected})")
        if engine.weights_version != 3 or gen.weights_version != 3:
            problems.append(
                f"engines moved off version 3 after a corrupt publish "
                f"(engine={engine.weights_version}, "
                f"gen={gen.weights_version})")
        out = engine.infer_sync([reqs[0]], timeout=30)
        if not np.array_equal(out[0], inf_refs[3][0]):
            problems.append("post-corruption response no longer bitwise "
                            "at version 3")
    finally:
        stop.set()
        for t in threads:
            t.join(30)
        watcher.stop()
        srv.close()
    engine.drain(timeout=30)
    gen.drain(timeout=60)
    stats = engine.stats()
    gen_stats = gen.stats()
    engine.close()
    gen.close()

    # -- part-one gates ----------------------------------------------------
    version_set = set(scales)
    for i, res in inf_outcomes:
        if isinstance(res, Exception):
            problems.append(f"inference request {i} failed under swap "
                            f"fire: {type(res).__name__}: {res}")
        elif not any(np.array_equal(res, inf_refs[v][i])
                     for v in version_set):
            problems.append(
                f"inference request {i}: response matches NO published "
                f"version (a swap tore a batch)")
    stable = 0
    for i, v0, v1, res in gen_outcomes:
        if isinstance(res, Exception):
            problems.append(f"generation request {i} failed under swap "
                            f"fire: {type(res).__name__}: {res}")
        elif v0 == v1 and v0 in version_set:
            stable += 1
            if res != gen_refs[v0][i]:
                problems.append(
                    f"generation request {i} ran entirely under "
                    f"version {v0} but tokens differ from its serial "
                    f"reference: {res} != {gen_refs[v0][i]}")
    if stable < 1:
        problems.append("no generation request ran inside a single "
                        "weights version (fire windows too short)")
    if probes[0] < 20:
        problems.append(f"readiness poller made only {probes[0]} probes")
    if ready_bad:
        problems.append(f"readiness went red during swaps: "
                        f"{ready_bad[:3]} ({len(ready_bad)} probes)")
    if not versions_seen <= {0, 1, 2, 3}:
        problems.append(f"/healthz exposed unexpected weights versions: "
                        f"{sorted(versions_seen)}")
    if monitor.get_stat("serving.swap.applied") != 3:
        problems.append(f"serving.swap.applied="
                        f"{monitor.get_stat('serving.swap.applied')}, "
                        f"expected 3")
    if monitor.get_stat("serving.swap.rejected") != 1:
        problems.append(f"serving.swap.rejected="
                        f"{monitor.get_stat('serving.swap.rejected')}, "
                        f"expected 1")
    if stats["recompiles_after_warmup"] != 0:
        problems.append(f"inference hot path recompiled "
                        f"{stats['recompiles_after_warmup']}x across "
                        f"swaps")
    if gen_stats["recompiles_after_warmup"] != 0:
        problems.append(f"decode hot path recompiled "
                        f"{gen_stats['recompiles_after_warmup']}x "
                        f"across swaps")
    if stats["counters"].get("closed_stranded", 0):
        problems.append(f"{stats['counters']['closed_stranded']} "
                        f"futures stranded at close")
    if gen_stats["page_pool"]["in_use"] != 0 \
            or gen_stats["counters"]["pages_allocated"] \
            != gen_stats["counters"]["pages_freed"]:
        problems.append(
            f"page pool not reclaimed: in_use="
            f"{gen_stats['page_pool']['in_use']}, "
            f"{gen_stats['counters']['pages_allocated']} allocated vs "
            f"{gen_stats['counters']['pages_freed']} freed")
    if verbose:
        print(f"swap fire: {len(inf_outcomes)} inference + "
              f"{len(gen_outcomes)} generation requests "
              f"({stable} version-stable), "
              f"swaps={stats['counters']['weight_swaps']}/"
              f"{gen_stats['counters']['weight_swaps']}, probes="
              f"{probes[0]}")

    # -- part two: supervised replica crash mid-traffic --------------------
    if supervised and not problems:
        problems.extend(_swap_supervised(prefixes[0], inf_refs[0], reqs,
                                         workdir, verbose))

    if own_tmp:
        shutil.rmtree(workdir, ignore_errors=True)
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("chaos swap OK: three live weight swaps applied under "
          "concurrent traffic (bitwise per version, readiness green, "
          "0 recompiles), a corrupted snapshot rejected with the old "
          "weights still serving, and a crashed supervised replica "
          "restarted with clients riding through")
    return 0


def _swap_supervised(prefix, refs, reqs, workdir, verbose):
    """Part two of :func:`swap_main`: the supervised-replica crash.
    Returns a list of failure strings."""
    import socket
    import threading
    import time

    from paddle_tpu import serving
    from paddle_tpu.distributed import ServingSupervisor
    from paddle_tpu.utils import monitor

    out = []
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    url = f"http://127.0.0.1:{port}"
    state_file = os.path.join(workdir, "sv_state")
    stop_file = os.path.join(workdir, "sv_stop")

    sv = ServingSupervisor(
        _swap_serving_entry, args=(prefix, port, state_file, stop_file),
        name="swapchaos", health_url=f"{url}/healthz",
        ready_poll_s=0.1, probe_timeout_s=2.0, ready_fail_budget=50,
        hang_deadline_s=300.0, startup_timeout_s=240.0, poll_s=0.1,
        backoff_s=0.1, backoff_max_s=0.5,
        crash_window_s=600.0, crash_budget=3,
        child_env={"JAX_PLATFORMS": "cpu"}, workdir=workdir)
    box = {}

    def run_sv():
        try:
            box["result"] = sv.run()
        except Exception as e:  # noqa: BLE001 - surfaced below
            box["error"] = e

    svt = threading.Thread(target=run_sv, daemon=True)
    svt.start()

    def wait_ready(deadline_s):
        deadline = time.monotonic() + deadline_s
        c = serving.Client(url, timeout=5, reconnect_backoff_s=0.05)
        while time.monotonic() < deadline:
            try:
                if c.healthz().get("ready"):
                    return True
            except Exception:  # noqa: BLE001 - replica not up yet
                pass
            time.sleep(0.1)
        return False

    successes, failures = [], []
    b_stop = threading.Event()

    def b_client(idx):
        c = serving.Client(url, timeout=10, reconnect_backoff_s=0.1)
        k = idx
        while not b_stop.is_set():
            i = k % len(reqs)
            k += 2
            try:
                got = c.predict([reqs[i]])
                successes.append((i, np.asarray(got[0],
                                                dtype=np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                failures.append((i, e))
            time.sleep(0.01)

    try:
        if not wait_ready(240.0):
            return ["supervised replica never became ready"]
        clients = [threading.Thread(target=b_client, args=(c,),
                                    daemon=True) for c in range(2)]
        for t in clients:
            t.start()
        # the first incarnation self-crashes ~1s after ready; wait for
        # the supervisor to notice and restart it
        deadline = time.monotonic() + 120
        while monitor.get_stat("supervisor.serving.restarts") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        if monitor.get_stat("supervisor.serving.restarts") < 1:
            b_stop.set()
            return ["replica crash never triggered a supervised "
                    "restart"]
        if not wait_ready(240.0):
            b_stop.set()
            return ["restarted replica never became ready again"]
        # post-restart: fresh client, serial bitwise pass
        c = serving.Client(url, timeout=10)
        for i in range(3):
            got = c.predict([reqs[i]])
            arr = np.asarray(got[0], dtype=np.float32)
            if not np.array_equal(arr, refs[i]):
                out.append(f"post-restart response {i} not bitwise "
                           f"(max |d|={np.abs(arr - refs[i]).max():.3e})")
        b_stop.set()
        for t in clients:
            t.join(30)
    finally:
        b_stop.set()
        with open(stop_file, "w") as f:
            f.write("1")
        svt.join(300)
        sv.stop()

    if "error" in box:
        out.append(f"supervisor died: {type(box['error']).__name__}: "
                   f"{box['error']}")
        return out
    result = box.get("result")
    if result is None:
        out.append("supervisor did not finish after the stop file")
        return out
    if not result.clean_exit or result.attempts != 2:
        out.append(f"expected 2 incarnations ending cleanly, got "
                   f"attempts={result.attempts} "
                   f"clean_exit={result.clean_exit}")
    reasons = [r["reason"] for r in result.exit_history]
    if not reasons or "crash(exit=9)" not in reasons[0]:
        out.append(f"first exit reason {reasons[:1]} != crash(exit=9)")
    if monitor.get_stat("supervisor.serving.starts") != 2:
        out.append(f"supervisor.serving.starts="
                   f"{monitor.get_stat('supervisor.serving.starts')}, "
                   f"expected 2")
    if monitor.get_stat("supervisor.serving.ready_up") < 2:
        out.append("readiness never came up twice (no observable "
                   "not-ready -> re-warm -> ready transition)")
    if monitor.get_stat("client.reconnects") < 1:
        out.append("clients never exercised the reconnect path "
                   "(client.reconnects=0)")
    for i, arr in successes:
        if not np.array_equal(arr, refs[i]):
            out.append(f"ride-through response {i} not bitwise")
            break
    if not successes:
        out.append("no client request succeeded across the restart")
    bad = [f for _, f in failures
           if not isinstance(f, (serving.ServingError, OSError))]
    if bad:
        out.append(f"restart-window failures were not clean connection "
                   f"errors: {[type(b).__name__ for b in bad[:3]]}")
    if verbose:
        print(f"supervised: {len(successes)} ok / {len(failures)} "
              f"refused during restart, reconnects="
              f"{monitor.get_stat('client.reconnects')}, "
              f"exits={reasons}")
    return out


# ---------------------------------------------------------------------------
# Data-plane anomaly (ISSUE 15): NaN feeds, non-finite grad buckets and a
# corrupted int8 wire payload -> sentry skip -> quarantine -> rollback
# ---------------------------------------------------------------------------

# One rule per corruption class, all replayable (host rules via hit
# accounting, in-graph rules via deterministic run windows baked into
# the compiled step):
#  - a NaN batch from the loader (cleared by one skip+re-delivery);
#  - an inf gradient before reduction (in-graph, run 7);
#  - a NaN int8 block-scale on the wire (in-graph, run 9);
#  - a poisoned-feed burst right after the step-8 snapshot: batch 9
#    keeps flagging past the skip budget (quarantine), batch 10 flags
#    immediately after (rollback to the snapshot).
ANOMALY_CHAOS_SPEC = (
    "dataloader.batch:action=corrupt,mode=nan,count=1,match=batch=2;"
    "executor.grads:action=corrupt,mode=inf,count=1,after=6;"
    "grad_comm.wire:action=corrupt,mode=nan,count=1,after=8,"
    "tensor=*scales*;"
    "dataloader.batch:action=corrupt,mode=nan,count=3,match=batch=9;"
    "dataloader.batch:action=corrupt,mode=inf,count=1,match=batch=10")

AN_BATCH = 32          # rows per batch (divisible by dp=8)


class AnomalyDataset:
    """12 deterministic regression batches (module-level so any loader
    path can pickle it)."""

    def __init__(self, n_batches=12, batch=AN_BATCH, dim=8):
        rng = np.random.RandomState(13)
        self.x = rng.standard_normal(
            (n_batches * batch, dim)).astype(np.float32)
        self.y = (self.x @ rng.standard_normal((dim, 1))
                  ).astype(np.float32)

    def __len__(self):
        return self.x.shape[0]

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _anomaly_build(lr=0.05):
    """Fleet-sharded static program with int8+error-feedback grad_comm
    — the configuration whose block scales and residual carry a single
    NaN would poison without the sentry."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import distributed as dist, optimizer

    paddle.seed(1234)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        pred = paddle.static.nn.fc(x, 8)
        pred = paddle.static.nn.fc(F.relu(pred), 1)
        loss = F.mse_loss(pred, y)
        f = dist.fleet
        strat = dist.DistributedStrategy()
        strat.grad_comm = {"dtype": "int8", "error_feedback": True,
                           "scatter_threshold_KB": 0.01,
                           "block_size": 64}
        f.init(is_collective=True, strategy=strat)
        opt = f.distributed_optimizer(optimizer.Adam(learning_rate=lr))
        opt.minimize(loss)
    return main, loss, paddle.static.Executor()


def _anomaly_run(loader, exe, main, loss, steps, policy=None,
                 store=None, objects=None, save_every=4, verbose=False):
    """The training loop both the reference and chaos runs share:
    batch ``k`` drives applied step ``k``; the chaos run additionally
    reacts to the policy's ladder (retry / advance / rewind)."""
    import numpy as np

    losses = {}
    applied = cursor = 0
    while applied < steps:
        xb, yb = loader.fetch_batch(cursor)
        if policy is not None:
            policy.note_batch(cursor)
        val = float(exe.run(main, feed={"x": np.asarray(xb),
                                        "y": np.asarray(yb)},
                            fetch_list=[loss])[0])
        act = policy.poll() if policy is not None else "ok"
        if verbose:
            print(f"  step {applied} batch {cursor}: {act} "
                  f"loss={val:.6f}")
        if act == "ok":
            losses[applied] = val
            applied += 1
            cursor += 1
            if store is not None and applied % save_every == 0 \
                    and applied < steps:
                store.save(0, objects, step=applied, kind="step")
        elif act == "skip":
            continue                      # re-deliver the same batch
        elif act == "quarantine":
            cursor += 1                   # blamed: move past it
        elif act == "rollback":
            applied = cursor = policy.resume_step
    return [losses[s] for s in range(steps)]


def anomaly_main(steps=12, save_every=4, verbose=False, workdir=None):
    """Data-plane fault-tolerance gate; returns 0 on success, 1 on
    failure.  Under injected NaN feeds, a non-finite gradient bucket,
    one corrupted int8 wire payload, and a poisoned-feed burst, an
    int8+error-feedback training run must finish with its applied-step
    loss trajectory matching the fault-free run — via in-graph sentry
    skips, one batch quarantine and one snapshot rollback, with zero
    manual intervention, and every decision auditable from
    ``anomaly.*`` stats and the rollback flight dump."""
    import json

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability
    from paddle_tpu.distributed import AnomalyPolicy
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.io import DataLoader
    from paddle_tpu.testing import fault
    from paddle_tpu.utils import monitor
    from paddle_tpu.utils.checkpoint import SnapshotStore

    import jax
    if len(jax.devices()) < 8:
        print("FAIL: anomaly scenario needs 8 devices "
              "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)
        return 1

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_anomaly_")
    loader = DataLoader(AnomalyDataset(), batch_size=AN_BATCH,
                        shuffle=False)
    was_static = paddle.in_static_mode() \
        if hasattr(paddle, "in_static_mode") else False
    paddle.enable_static()
    old_sentry = paddle.get_flags("anomaly_sentry")
    paddle.set_flags({"anomaly_sentry": True})
    policy = None
    try:
        # -- reference: fault-free run, same batch schedule ---------------
        init_mesh({"dp": 8})
        main, loss, exe = _anomaly_build()
        init_mesh({"dp": 8})
        ref = _anomaly_run(loader, exe, main, loss, steps)
        ref_params = {k: np.asarray(v).copy() for k, v in
                      exe.sharded_state(main)._getter()
                      ["params"].items()}
        exe.close()
        paddle.static.reset_default_programs()
        if verbose:
            print(f"reference: {ref}")

        # -- chaos run ----------------------------------------------------
        monitor.stat_reset()
        flight_path = os.path.join(workdir, "anomaly_flight.json")
        observability.enable(capacity=4096)
        observability.install_flight_recorder(path=flight_path,
                                             catch_sigterm=False)
        store = SnapshotStore(f"{workdir}/ckpt")
        # arm BEFORE the build: in-graph corrupt rules are baked into
        # the compiled step at its (single) compile
        fault.arm(ANOMALY_CHAOS_SPEC, seed=0)
        init_mesh({"dp": 8})
        main, loss, exe = _anomaly_build()
        init_mesh({"dp": 8})
        objects = {"train": exe.sharded_state(main)}
        policy = AnomalyPolicy(store=store, objects=objects,
                               skip_budget=2, rollback_budget=1,
                               sync=True).install()
        try:
            got = _anomaly_run(loader, exe, main, loss, steps,
                               policy=policy, store=store,
                               objects=objects, save_every=save_every,
                               verbose=verbose)
        finally:
            fault.disarm()
        sentry = exe.sentry_stats(main)
        compiles = exe.compile_count
        got_params = {k: np.asarray(v).copy() for k, v in
                      exe.sharded_state(main)._getter()
                      ["params"].items()}
        exe.close()
        paddle.static.reset_default_programs()
        if verbose:
            print(f"chaos:     {got}")
            print(f"policy:    {policy.result()}")
            print(f"sentry:    {sentry}")

        # -- gates --------------------------------------------------------
        problems = []
        stats = monitor.all_stats()
        res = policy.result()
        try:
            np.testing.assert_allclose(got, ref, rtol=1e-5)
        except AssertionError as e:
            problems.append(f"applied-step loss trajectory diverged "
                            f"from the fault-free run: {e}")
        if compiles != 1:
            problems.append(f"sentry/chaos run compiled {compiles}x "
                            f"(want 1 — no recompiles after warmup)")
        # the ladder must have exercised every rung exactly as staged
        if res["skips"] != 5:
            problems.append(f"anomaly skips={res['skips']}, expected 5 "
                            f"(NaN feed, inf grads, wire NaN, 2 burst "
                            f"skips)")
        if res["quarantines"] != 1 or not res["ledger"] \
                or res["ledger"][0]["batch"] != 9:
            problems.append(f"quarantine ledger wrong: "
                            f"{res['ledger']} (expected batch 9 "
                            f"blamed once)")
        if res["rollbacks"] != 1 or res["resume_step"] != 8:
            problems.append(f"expected 1 rollback to step 8, got "
                            f"{res['rollbacks']} to "
                            f"{res['resume_step']}")
        # ...and be visible in monitor stats
        for stat, want in (("anomaly.skips", 5),
                           ("anomaly.quarantines", 1),
                           ("anomaly.rollbacks", 1)):
            if stats.get(stat, 0) != want:
                problems.append(f"{stat}={stats.get(stat, 0)}, "
                                f"expected {want}")
        if not stats.get("grad_comm.nonfinite_blocks", 0):
            problems.append("grad_comm.nonfinite_blocks never counted "
                            "(quantize-time guard untested)")
        # every injected corruption actually fired (in-graph points
        # count one fire per matched tensor site, so >= 1)
        if stats.get("fault.fired.dataloader.batch", 0) != 5:
            problems.append(
                f"fault.fired.dataloader.batch="
                f"{stats.get('fault.fired.dataloader.batch', 0)}, "
                f"expected 5")
        for point in ("fault.fired.executor.grads",
                      "fault.fired.grad_comm.wire"):
            if stats.get(point, 0) < 1:
                problems.append(f"{point} never fired")
        # device-side skipped counter = every flagged step (5 skips +
        # the quarantine fire + the rollback fire); it rides the aux
        # carry as a diagnostic and the restore deliberately keeps it
        # (like the EF residuals, it is an accumulator, not state)
        if sentry is None or sentry["skipped_steps"] != 7:
            problems.append(f"sentry skipped_steps="
                            f"{None if sentry is None else sentry['skipped_steps']}"
                            f", expected 7 (one per flagged step)")
        # final weights match the fault-free run
        for k in ref_params:
            if not np.allclose(got_params[k], ref_params[k],
                               rtol=1e-5, atol=0):
                problems.append(
                    f"final param {k} diverged from the fault-free "
                    f"run (max |d|="
                    f"{np.abs(got_params[k] - ref_params[k]).max():.3e})")
        # the rollback must have left an annotated flight dump
        if not os.path.exists(flight_path):
            problems.append("rollback left no flight dump")
        else:
            with open(flight_path) as f:
                box = json.load(f)
            if box.get("reason") != "anomaly.rollback":
                problems.append(f"flight dump reason "
                                f"{box.get('reason')!r} != "
                                f"'anomaly.rollback'")
            extra = box.get("extra") or {}
            led = extra.get("ledger") or []
            if not led or led[0].get("batch") != 9:
                problems.append(f"flight dump ledger {led} does not "
                                f"blame batch 9")
            if extra.get("anomaly", {}).get("resume_step") != 8:
                problems.append("flight dump lacks the rollback's "
                                "resume_step annotation")
        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("chaos anomaly OK: NaN feed, inf grad bucket and a "
              "corrupted int8 wire payload were sentry-skipped "
              "(bitwise no-ops), the poisoned-feed burst was "
              "quarantined then rolled back to the step-8 snapshot, "
              "and the applied-step loss trajectory matches the "
              "fault-free run with zero manual intervention")
        return 0
    finally:
        if policy is not None:
            policy.uninstall()
        paddle.set_flags(old_sentry)
        from paddle_tpu import observability as _obs
        _obs.uninstall_flight_recorder()
        _obs.disable()
        if not was_static:
            paddle.disable_static()
        import paddle_tpu.static as _st
        _st.reset_default_programs()
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Supervised self-healing (ISSUE 13): hang -> watchdog kill -> resume;
# crash -> restart onto a SMALLER mesh via reshard restore
# ---------------------------------------------------------------------------

def _supervised_entry(workdir, steps, save_every):
    """The training entrypoint the supervisor keeps alive.  Stateless
    by design: every incarnation re-detects the visible device count,
    builds the (unchanged) fleet-sharded program on mesh ``{dp: ndev}``,
    auto-resumes from the newest intact snapshot through the
    ShardedState reshard path, and trains with step-cadence snapshots.
    Faults arrive via ``FLAGS_fault_spec`` in the spawn environment."""
    import json

    import numpy as np

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.utils.checkpoint import TrainEpochRange

    ndev = len(jax.devices())            # re-detect the visible mesh
    paddle.enable_static()
    init_mesh({"dp": ndev})
    main, loss, exe = _reshard_build()
    init_mesh({"dp": ndev})
    feed = _reshard_feed()
    r = TrainEpochRange(1, f"{workdir}/ckpt", save_every_steps=save_every,
                        train=exe.sharded_state(main))
    # the step log is the parent-visible record: resume markers prove
    # which snapshot (and device count) each incarnation started from,
    # step lines carry the losses the parity gate checks
    with open(f"{workdir}/steps.jsonl", "a", buffering=1) as log:
        for _epoch in r:
            log.write(json.dumps({"event": "resume",
                                  "step": r.resume_step,
                                  "devices": ndev}) + "\n")
            for step in range(r.resume_step, steps):
                val = float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0])
                log.write(json.dumps({"step": step, "loss": val,
                                      "devices": ndev}) + "\n")
                r.step()
    exe.close()


def _sv_flaky_entry(state_file, failures=2, code=5):
    """Supervisor test fixture (module-level so spawn children can
    unpickle it): exit ``code`` for the first ``failures`` incarnations
    — the counter persists in ``state_file`` — then exit cleanly."""
    n = 0
    if os.path.exists(state_file):
        n = int(open(state_file).read())
    with open(state_file, "w") as f:
        f.write(str(n + 1))
    if n < failures:
        sys.exit(code)


def _sv_slow_start_entry(state_file):
    """Supervisor test fixture: the first incarnation beats at step
    scale then crashes; the second stays beat-silent for a while (a
    restart's recompile wall) before finishing.  The watchdog must
    judge that quiet start against ``startup_timeout_s``, not the
    step-scale deadline its retained interval window would give."""
    import time

    from paddle_tpu.distributed.supervisor import current_heartbeat

    hb = current_heartbeat()
    if not os.path.exists(state_file):
        with open(state_file, "w") as f:
            f.write("1")
        for i in range(10):
            hb.beat(i)
            time.sleep(0.02)
        sys.exit(3)
    time.sleep(2.0)                  # 'compiling': no step beats
    hb.beat(0)


def _sv_hang_entry(state_file, beats=6, interval=0.05):
    """Supervisor test fixture: beat the heartbeat by hand for a while,
    then wedge (sleep 600s) on the FIRST incarnation; exit cleanly on
    the second — a hang the watchdog must clear exactly once."""
    import time

    from paddle_tpu.distributed.supervisor import current_heartbeat

    if os.path.exists(state_file):
        return
    with open(state_file, "w") as f:
        f.write("1")
    hb = current_heartbeat()
    for i in range(beats):
        hb.beat(i)
        time.sleep(interval)
    time.sleep(600)


def supervise_main(steps=14, save_every=2, hang_after=5, crash_after=4,
                   verbose=False, workdir=None):
    """Self-healing training gate; returns 0 on success, 1 on failure.

    One supervised job survives, with zero manual intervention:

    1. an injected mid-step hang (``executor.step_hang`` sleep fault)
       — the watchdog misses heartbeats, escalates SIGTERM→SIGKILL,
       and restarts; the job resumes from the latest step-cadence
       snapshot;
    2. an injected hard crash (``executor.run`` exit fault) — the
       restarted incarnation sees only 4 of the original 8 devices and
       resumes via the SnapshotStore/ShardedState reshard path
       (mesh 8 → 4 is a restart, not an outage);

    and the assembled per-step loss trajectory matches an
    uninterrupted fault-free run (rtol 1e-5 — dp reduction order
    differs across mesh sizes).  The watchdog kill, restart reasons and
    snapshot fallback must all be visible in ``supervisor.*`` stats,
    the exit history, and the kill-time flight dump.
    """
    import json

    import paddle_tpu as paddle
    from paddle_tpu.distributed.supervisor import (StepWatchdog,
                                                   TrainingSupervisor)
    from paddle_tpu.utils import monitor

    import jax
    if len(jax.devices()) < 8:
        print("FAIL: supervise scenario needs 8 devices "
              "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)
        return 1

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_supervise_")
    was_static = paddle.in_static_mode() \
        if hasattr(paddle, "in_static_mode") else False

    def child_env(attempt):
        ndev = 8 if attempt < 2 else 4   # the replacement pod is smaller
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
            "FLAGS_fault_spec": "",
        }
        if attempt == 0:
            # wedge one step for 600s: only the watchdog can clear it
            env["FLAGS_fault_spec"] = (
                f"executor.step_hang:count=1,after={hang_after},"
                f"action=sleep,secs=600")
        elif attempt == 1:
            # hard crash: no boundary save, no SystemExit — just gone
            env["FLAGS_fault_spec"] = (
                f"executor.run:count=1,after={crash_after},"
                f"action=exit,code=7")
        return env

    try:
        # -- reference: uninterrupted run on the full mesh ----------------
        from paddle_tpu.distributed.mesh import init_mesh
        import numpy as np
        paddle.enable_static()
        init_mesh({"dp": 8})
        main, loss, exe = _reshard_build()
        init_mesh({"dp": 8})
        feed = _reshard_feed()
        ref_losses = [float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0])
                      for _ in range(steps)]
        exe.close()
        paddle.static.reset_default_programs()
        if verbose:
            print(f"reference (mesh dp=8): {ref_losses}")

        # -- supervised chaos run -----------------------------------------
        from paddle_tpu.distributed.supervisor import SupervisorGaveUp
        monitor.stat_reset()
        sv = TrainingSupervisor(
            _supervised_entry, args=(workdir, steps, save_every),
            name="chaos",
            watchdog=StepWatchdog(multiplier=8.0, min_deadline_s=3.0,
                                  max_deadline_s=240.0),
            startup_timeout_s=240.0, hang_grace_s=2.0, poll_s=0.2,
            backoff_s=0.1, backoff_max_s=1.0,
            crash_window_s=600.0, crash_budget=4,
            child_env=child_env, workdir=workdir)
        try:
            result = sv.run()
        except SupervisorGaveUp as e:
            print(f"FAIL: supervisor gave up instead of self-healing: "
                  f"{e}", file=sys.stderr)
            return 1

        problems = []
        if not result.clean_exit:
            problems.append("supervised job did not end cleanly")
        if result.attempts != 3:
            problems.append(f"expected exactly 3 incarnations "
                            f"(hang, crash, finish), got "
                            f"{result.attempts}")
        reasons = [r["reason"] for r in result.exit_history]
        if not reasons or reasons[0] != "hang":
            problems.append(f"first restart reason {reasons[:1]} != "
                            f"'hang' (watchdog kill)")
        if len(reasons) < 2 or "crash(exit=7)" not in reasons[1]:
            problems.append(f"second restart reason {reasons[1:2]} != "
                            f"crash(exit=7)")

        # supervisor decisions must be observable in monitor stats
        stats = monitor.all_stats()
        if stats.get("supervisor.hang_kills", 0) < 1:
            problems.append("supervisor.hang_kills stat missing")
        if stats.get("supervisor.restarts", 0) != 2:
            problems.append(f"supervisor.restarts="
                            f"{stats.get('supervisor.restarts', 0)}, "
                            f"expected 2")
        if stats.get("supervisor.starts", 0) != 3:
            problems.append(f"supervisor.starts="
                            f"{stats.get('supervisor.starts', 0)}, "
                            f"expected 3")

        # the kill-time flight dump names the restart reason
        kill_dump = os.path.join(workdir, "supervisor_kill_a0.json")
        if not os.path.exists(kill_dump):
            problems.append("watchdog kill left no flight dump")
        else:
            with open(kill_dump) as f:
                box = json.load(f)
            if box.get("reason") != "supervisor.hang":
                problems.append(f"flight dump reason "
                                f"{box.get('reason')!r} != "
                                f"'supervisor.hang'")
            extra = box.get("extra") or {}
            if extra.get("restart_reason") != "hang" \
                    or extra.get("attempt") != 0:
                problems.append("flight dump extra lacks the annotated "
                                "restart reason/attempt")

        # the step log proves the resume path: three incarnations, the
        # last one on 4 devices resuming from a NONZERO snapshot step
        resumes, rows = [], {}
        with open(os.path.join(workdir, "steps.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "resume":
                    resumes.append(rec)
                else:
                    rows[rec["step"]] = rec   # last write wins
        if len(resumes) != 3:
            problems.append(f"expected 3 resume markers, got "
                            f"{len(resumes)}: {resumes}")
        else:
            if resumes[0]["step"] != 0 or resumes[0]["devices"] != 8:
                problems.append(f"first incarnation should start fresh "
                                f"on 8 devices: {resumes[0]}")
            if resumes[1]["devices"] != 8 or resumes[1]["step"] <= 0:
                problems.append(f"post-hang incarnation should resume "
                                f"a step snapshot on 8 devices: "
                                f"{resumes[1]}")
            if resumes[2]["devices"] != 4 or resumes[2]["step"] \
                    <= resumes[1]["step"]:
                problems.append(f"post-crash incarnation should "
                                f"reshard-resume on 4 devices past the "
                                f"previous snapshot: {resumes[2]}")
        if verbose:
            print(f"resumes: {resumes}")
            print(f"exit history: {result.exit_history}")

        # loss-trajectory parity with the fault-free run
        missing = [s for s in range(steps) if s not in rows]
        if missing:
            problems.append(f"steps never completed: {missing}")
        else:
            got = [rows[s]["loss"] for s in range(steps)]
            try:
                np.testing.assert_allclose(got, ref_losses, rtol=1e-5)
            except AssertionError as e:
                problems.append(
                    f"supervised loss trajectory diverged from the "
                    f"fault-free run: {e}")
            if verbose:
                print(f"supervised:  {got}")

        if problems:
            for p in problems:
                print(f"FAIL: {p}", file=sys.stderr)
            return 1
        print("chaos supervise OK: injected hang watchdog-killed "
              "(SIGTERM->SIGKILL) and resumed from a step snapshot; "
              "injected crash restarted onto mesh dp=4 via reshard "
              "restore; loss trajectory matches the fault-free run "
              "with zero manual intervention")
        return 0
    finally:
        if not was_static:
            paddle.disable_static()
        import paddle_tpu.static as _st
        _st.reset_default_programs()
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Registry chaos (ISSUE 19): the multi-model control plane under fire —
# a live weight swap on one model, an unload/reload of the other mid-
# traffic, and a supervised two-model replica crash ride-through
# ---------------------------------------------------------------------------

def _registry_serving_entry(prefix_a, prefix_b, port, state_file,
                            stop_file):
    """Supervised two-model registry replica (module-level so spawn can
    pickle it).  Binds the HTTP plane not-ready with a ModelRegistry,
    loads + warms both models, marks ready.  The FIRST incarnation
    hard-crashes about a second after going ready — with the parent's
    clients routing to both models — so the supervisor must restart it
    and the replacement must reload BOTH models before traffic
    recovers."""
    import threading
    import time

    from paddle_tpu import serving

    reg = serving.ModelRegistry(max_inflight=64)
    srv = serving.ServingServer(None, port=port, ready=False,
                                registry=reg).start()
    kw = {"max_batch_size": 8, "batch_timeout_ms": 5.0}
    reg.load("modelA", prefix_a, engine_kwargs=dict(kw))
    reg.load("modelB", prefix_b, engine_kwargs=dict(kw))
    srv.mark_ready()
    if not os.path.exists(state_file):
        with open(state_file, "w") as f:
            f.write("1")

        def _die():
            time.sleep(1.0)
            os._exit(9)         # a hard replica crash, mid-traffic
        threading.Thread(target=_die, daemon=True).start()
    while not os.path.exists(stop_file):
        time.sleep(0.05)
    srv.close()
    reg.close(timeout=10.0)


def registry_main(requests=16, clients=2, verbose=False, workdir=None,
                  supervised=True):
    """Two-model control-plane gate; returns 0 on success, 1 on failure.

    Part one (in-process, HTTP clients routing by model name): a
    :class:`~paddle_tpu.serving.ModelRegistry` serves ``modelA``
    (inference + generation engines) and ``modelB`` (inference) behind
    one :class:`ServingServer` while client threads hammer both.
    Under that fire: (1) a WeightWatcher hot-swaps modelA's inference
    weights — every A response must be bitwise-correct for exactly one
    published version and B's responses must never move; (2) modelB is
    unloaded mid-traffic — in-flight B requests finish bitwise, later
    ones get a clean :class:`UnknownModel` (the HTTP 404), never a hang
    — then reloaded, after which B serves bitwise again.  Final gates:
    zero hot-path recompiles across the swap, zero stranded futures,
    modelA's unload reports its generation page pool fully reclaimed,
    and the registry counters saw the unknown-model window.

    Part two (``supervised=True``): a two-model registry replica under
    a :class:`ServingSupervisor` hard-crashes mid-traffic; the
    supervisor restarts it, the replacement reloads BOTH models, and
    clients ride through on the reconnect path with post-restart
    responses bitwise for each model."""
    import threading
    import time

    from paddle_tpu import inference, serving
    from paddle_tpu.serving.hotswap import WeightWatcher, publish_weights
    from paddle_tpu.utils import monitor
    from paddle_tpu.utils.checkpoint import SnapshotStore

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_registry_")
    problems = []
    monitor.stat_reset()

    # -- per-(model, version) bitwise references ---------------------------
    prefix_a0 = _scaled_artifact(1.0, workdir, "a0")
    prefix_a1 = _scaled_artifact(0.25, workdir, "a1")
    prefix_b = _scaled_artifact(0.5, workdir, "b")
    preds = {k: inference.create_predictor(inference.Config(p))
             for k, p in (("a0", prefix_a0), ("a1", prefix_a1),
                          ("b", prefix_b))}
    rng = np.random.RandomState(23)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 5), 8)) / 4.0)
            .astype(np.float32) for _ in range(requests)]
    refs = {k: [np.asarray(p.run([x])[0]) for x in reqs]
            for k, p in preds.items()}
    prompts = [rng.randint(0, 32, rng.randint(1, 9)).tolist()
               for _ in range(4)]
    budgets = [int(rng.randint(3, 7)) for _ in prompts]
    ref_gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                       page_size=4, max_context=64)
    ref_gen.warmup()
    gen_refs = [ref_gen.generate_sync(prompts[i], timeout=60,
                                      max_new_tokens=budgets[i],
                                      temperature=0.7, seed=i)
                for i in range(len(prompts))]
    ref_gen.close()

    # -- the registry under test -------------------------------------------
    reg = serving.ModelRegistry(max_inflight=64)
    eng_a = serving.InferenceEngine(preds["a0"], max_batch_size=8,
                                    batch_timeout_ms=5.0,
                                    max_queue=8 * requests, name="modelA")
    eng_a.warmup()
    gen_a = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                     page_size=4, max_context=64,
                                     max_queue=256, name="modelA")
    gen_a.warmup()
    store = SnapshotStore(os.path.join(workdir, "weights_a"))
    watcher = WeightWatcher(store, engine=eng_a, poll_s=0.05).start()
    reg.register("modelA", engine=eng_a, generation=gen_a,
                 watcher=watcher, weight=2.0)
    eng_b = serving.InferenceEngine(preds["b"], max_batch_size=8,
                                    batch_timeout_ms=5.0,
                                    max_queue=8 * requests, name="modelB")
    eng_b.warmup()
    reg.register("modelB", engine=eng_b)
    srv = serving.ServingServer(None, port=0, registry=reg).start()

    stop = threading.Event()
    a_out, b_out, g_out = [], [], []

    def a_client(idx):
        c = serving.Client(srv.url, model="modelA", timeout=30)
        k = idx
        while not stop.is_set():
            i = k % len(reqs)
            k += clients
            try:
                got = c.predict([reqs[i]])
                a_out.append((i, np.asarray(got[0], dtype=np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                a_out.append((i, e))

    def b_client(idx):
        c = serving.Client(srv.url, model="modelB", timeout=30)
        k = idx
        while not stop.is_set():
            i = k % len(reqs)
            k += clients
            try:
                got = c.predict([reqs[i]])
                b_out.append((i, np.asarray(got[0], dtype=np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                b_out.append((i, e))
            time.sleep(0.01)

    def g_client(idx):
        c = serving.Client(srv.url, model="modelA", timeout=60)
        k = idx
        while not stop.is_set():
            i = k % len(prompts)
            k += clients
            try:
                toks = c.generate(prompts[i],
                                  max_new_tokens=budgets[i],
                                  temperature=0.7, seed=i)
                g_out.append((i, toks))
            except Exception as e:  # noqa: BLE001 - gated below
                g_out.append((i, e))

    admin = serving.Client(srv.url, timeout=60)
    threads = [threading.Thread(target=f, args=(c,), daemon=True)
               for f in (a_client, b_client, g_client)
               for c in range(clients)]
    for t in threads:
        t.start()
    b_unknown_window = []
    try:
        time.sleep(0.4)                     # fire on (A=v0, B)

        # -- (1) live weight swap on modelA, B must not move -------------
        publish_weights(store, 1, artifact_prefix=prefix_a1)
        deadline = time.monotonic() + 60
        while watcher.version < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        if watcher.version != 1:
            problems.append(f"modelA swap not applied within 60s "
                            f"(last_error={watcher.last_error})")
        for i in range(3):
            got = admin.predict([reqs[i]], model="modelA")
            if not np.array_equal(np.asarray(got[0], np.float32),
                                  refs["a1"][i]):
                problems.append(f"modelA settled response {i} not "
                                f"bitwise at version 1")
            got = admin.predict([reqs[i]], model="modelB")
            if not np.array_equal(np.asarray(got[0], np.float32),
                                  refs["b"][i]):
                problems.append(f"modelB response {i} moved during "
                                f"modelA's swap")
        time.sleep(0.3)                     # fire on (A=v1, B)

        # -- (2) unload modelB mid-traffic, then reload ------------------
        mark = len(b_out)
        summary = admin.unload_model("modelB")
        if not summary.get("engine_drained"):
            problems.append(f"modelB unload did not drain cleanly: "
                            f"{summary}")
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:
            time.sleep(0.05)                # window where B is gone
        b_unknown_window = [r for _, r in b_out[mark:]
                            if isinstance(r, serving.UnknownModel)]
        if not b_unknown_window:
            problems.append("no B request saw a clean UnknownModel "
                            "while the model was unloaded")
        admin.load_model("modelB", prefix_b,
                         engine_kwargs={"max_batch_size": 8,
                                        "batch_timeout_ms": 5.0})
        reload_mark = len(b_out)
        time.sleep(0.4)                     # fire on the reloaded B
        post = [(i, r) for i, r in b_out[reload_mark:]
                if not isinstance(r, Exception)]
        if not post:
            problems.append("no B request succeeded after the reload")
    finally:
        stop.set()
        for t in threads:
            t.join(30)
        watcher.stop()
        srv.close()

    # -- part-one gates ----------------------------------------------------
    for i, res in a_out:
        if isinstance(res, Exception):
            problems.append(f"modelA request {i} failed under fire: "
                            f"{type(res).__name__}: {res}")
        elif not (np.array_equal(res, refs["a0"][i])
                  or np.array_equal(res, refs["a1"][i])):
            problems.append(f"modelA request {i}: response matches "
                            f"neither published version (a swap tore "
                            f"a batch)")
    clean_b = (serving.UnknownModel, serving.EngineClosed)
    for i, res in b_out:
        if isinstance(res, Exception):
            if not isinstance(res, clean_b):
                problems.append(f"modelB request {i} failed uncleanly "
                                f"under churn: {type(res).__name__}: "
                                f"{res}")
        elif not np.array_equal(res, refs["b"][i]):
            problems.append(f"modelB request {i} not bitwise")
    for i, res in g_out:
        if isinstance(res, Exception):
            problems.append(f"generation request {i} failed under "
                            f"fire: {type(res).__name__}: {res}")
        elif list(res) != list(gen_refs[i]):
            problems.append(f"generation request {i} tokens differ "
                            f"from the serial reference")
    if len(a_out) < 5 or len(g_out) < 2:
        problems.append(f"fire too thin: {len(a_out)} A requests, "
                        f"{len(g_out)} generations")

    # final teardown through the registry: stranded futures and page
    # reclamation are asserted from the unload summaries themselves
    summary_a = reg.unload("modelA", timeout=60)
    if not summary_a.get("pages_reclaimed", False):
        problems.append(f"modelA unload leaked pages: "
                        f"{summary_a.get('page_pool')}")
    stats_a = eng_a.stats()
    if stats_a["recompiles_after_warmup"] != 0:
        problems.append(f"modelA hot path recompiled "
                        f"{stats_a['recompiles_after_warmup']}x across "
                        f"the swap")
    if stats_a["counters"].get("closed_stranded", 0):
        problems.append(f"{stats_a['counters']['closed_stranded']} "
                        f"modelA futures stranded at close")
    gen_stats = gen_a.stats()
    if gen_stats["counters"]["pages_allocated"] \
            != gen_stats["counters"]["pages_freed"]:
        problems.append(
            f"page accounting: "
            f"{gen_stats['counters']['pages_allocated']} allocated vs "
            f"{gen_stats['counters']['pages_freed']} freed")
    if monitor.get_stat("registry.unknown_model") < 1:
        problems.append("registry.unknown_model never counted the "
                        "unload window")
    reg.close(timeout=30.0)
    if verbose:
        print(f"registry fire: {len(a_out)} A + {len(b_out)} B + "
              f"{len(g_out)} gen requests, "
              f"{len(b_unknown_window)} clean 404s in the unload "
              f"window, swap v{watcher.version}, "
              f"counters={reg.stats()['counters']}")

    # -- part two: supervised two-model replica crash ----------------------
    if supervised and not problems:
        problems.extend(_registry_supervised(prefix_a0, prefix_b,
                                             refs, reqs, workdir,
                                             verbose))

    if own_tmp:
        shutil.rmtree(workdir, ignore_errors=True)
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("chaos registry OK: modelA hot-swapped under two-model fire "
          "(bitwise per version, B unmoved), modelB unloaded mid-"
          "traffic (clean 404s, drained, no stranded futures) and "
          "reloaded, pages reclaimed, and a crashed two-model replica "
          "restarted with clients riding through")
    return 0


def _registry_supervised(prefix_a, prefix_b, refs, reqs, workdir,
                         verbose):
    """Part two of :func:`registry_main`: the supervised two-model
    replica crash.  Returns a list of failure strings."""
    import socket
    import threading
    import time

    from paddle_tpu import serving
    from paddle_tpu.distributed import ServingSupervisor
    from paddle_tpu.utils import monitor

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    url = f"http://127.0.0.1:{port}"
    state_file = os.path.join(workdir, "reg_sv_state")
    stop_file = os.path.join(workdir, "reg_sv_stop")

    sv = ServingSupervisor(
        _registry_serving_entry,
        args=(prefix_a, prefix_b, port, state_file, stop_file),
        name="regchaos", health_url=f"{url}/healthz",
        ready_poll_s=0.1, probe_timeout_s=2.0, ready_fail_budget=50,
        hang_deadline_s=300.0, startup_timeout_s=240.0, poll_s=0.1,
        backoff_s=0.1, backoff_max_s=0.5,
        crash_window_s=600.0, crash_budget=3,
        child_env={"JAX_PLATFORMS": "cpu"}, workdir=workdir)
    box = {}

    def run_sv():
        try:
            box["result"] = sv.run()
        except Exception as e:  # noqa: BLE001 - surfaced below
            box["error"] = e

    svt = threading.Thread(target=run_sv, daemon=True)
    svt.start()

    def wait_ready(deadline_s):
        deadline = time.monotonic() + deadline_s
        c = serving.Client(url, timeout=5, reconnect_backoff_s=0.05)
        while time.monotonic() < deadline:
            try:
                if c.healthz().get("ready"):
                    return True
            except Exception:  # noqa: BLE001 - replica not up yet
                pass
            time.sleep(0.1)
        return False

    successes, failures = [], []
    b_stop = threading.Event()

    def b_client(idx, model, ref_key):
        c = serving.Client(url, model=model, timeout=10,
                           reconnect_backoff_s=0.1)
        k = idx
        while not b_stop.is_set():
            i = k % len(reqs)
            k += 2
            try:
                got = c.predict([reqs[i]])
                successes.append((model, ref_key, i,
                                  np.asarray(got[0], np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                failures.append((model, i, e))
            time.sleep(0.01)

    out = []
    try:
        if not wait_ready(240.0):
            return ["supervised two-model replica never became ready"]
        clients = [threading.Thread(target=b_client,
                                    args=(n, m, rk), daemon=True)
                   for n, (m, rk) in enumerate((("modelA", "a0"),
                                                ("modelB", "b")))]
        for t in clients:
            t.start()
        deadline = time.monotonic() + 120
        while monitor.get_stat("supervisor.serving.restarts") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        if monitor.get_stat("supervisor.serving.restarts") < 1:
            b_stop.set()
            return ["two-model replica crash never triggered a "
                    "supervised restart"]
        if not wait_ready(240.0):
            b_stop.set()
            return ["restarted two-model replica never became ready "
                    "again"]
        # post-restart: fresh client, serial bitwise pass on BOTH models
        c = serving.Client(url, timeout=10)
        for model, key in (("modelA", "a0"), ("modelB", "b")):
            for i in range(3):
                got = c.predict([reqs[i]], model=model)
                arr = np.asarray(got[0], np.float32)
                if not np.array_equal(arr, refs[key][i]):
                    out.append(f"post-restart {model} response {i} "
                               f"not bitwise")
        try:
            c.predict([reqs[0]], model="nope")
            out.append("unknown model did not 404 on the restarted "
                       "replica")
        except serving.UnknownModel:
            pass
    finally:
        b_stop.set()
        with open(stop_file, "w") as f:
            f.write("1")
        sv.stop()
        svt.join(60)

    for model, key, i, arr in successes:
        if not np.array_equal(arr, refs[key][i]):
            out.append(f"{model} request {i} not bitwise during the "
                       f"ride-through")
    if not any(m == "modelA" for m, *_ in successes) \
            or not any(m == "modelB" for m, *_ in successes):
        out.append("ride-through traffic did not cover both models")
    if verbose:
        print(f"supervised ride-through: {len(successes)} successes, "
              f"{len(failures)} transient failures, restarts="
              f"{monitor.get_stat('supervisor.serving.restarts')}")
    return out


# ---------------------------------------------------------------------------
# Fleet observability (ISSUE 20): cross-process telemetry aggregation
# and one distributed /generate trace riding through a replica restart
# ---------------------------------------------------------------------------

def _fleet_gen_entry(port, state_file, stop_file):
    """Supervised generation replica for the fleet-observability gate
    (module-level so spawn can pickle it).  The spawn environment
    carries ``FLAGS_obs_spool_dir``/``FLAGS_obs_role`` staged by the
    supervisor, so this entrypoint spools telemetry with zero
    observability code of its own — which is exactly the property the
    gate exists to prove.  The FIRST incarnation hard-crashes
    (``os._exit``, no atexit: only already-spooled segments survive)
    about a second after going ready; the replacement serves until
    ``stop_file`` appears."""
    import threading
    import time

    from paddle_tpu import serving

    model = make_dyadic_lm()
    engine = serving.GenerationEngine(model, num_slots=4, page_size=4,
                                      max_context=64)
    srv = serving.ServingServer(None, port=port, generation=engine,
                                ready=False).start()
    engine.warmup()
    srv.mark_ready()
    if not os.path.exists(state_file):
        with open(state_file, "w") as f:
            f.write("1")

        def _die():
            time.sleep(1.0)
            os._exit(9)         # a hard replica crash, mid-traffic

        threading.Thread(target=_die, daemon=True).start()
    while not os.path.exists(stop_file):
        time.sleep(0.05)
    srv.close()
    engine.close()


def fleet_main(verbose=False, workdir=None):
    """Fleet-observability gate; returns 0 on success, 1 on failure.

    A :class:`ServingSupervisor`-managed generation replica (spooling
    telemetry via the staged ``FLAGS_obs_spool_dir``) hard-crashes
    mid-traffic and is restarted; a traffic thread with a PINNED trace
    id keeps issuing ``/generate`` requests through the outage.  Gates:

    * the spool holds per-process records for the parent AND both
      child incarnations (roles ``fleet-a0``/``fleet-a1``);
    * :func:`~paddle_tpu.observability.fleet.merged_chrome_trace`
      yields named, wall-time-aligned lanes for all of them, and the
      parent lane carries the supervisor ``restart`` event with the
      crash reason;
    * :func:`~paddle_tpu.observability.fleet.fleet_prometheus_text`
      labels every sample with ``{proc=...}``;
    * :func:`~paddle_tpu.observability.fleet.assemble_trace` stitches
      the pinned trace into ONE connected component spanning the
      parent pid and at least one server pid — the distributed span
      tree survives the process hop.
    """
    import json  # noqa: F401 - symmetry with sibling gates
    import socket
    import threading
    import time

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.core import flags
    from paddle_tpu.distributed import ServingSupervisor
    from paddle_tpu.observability import export as obs_export
    from paddle_tpu.observability import fleet as obs_fleet
    from paddle_tpu.utils import monitor

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_fleet_")
    spool = os.path.join(workdir, "spool")
    problems = []
    old_flags = {k: flags.get_flag(k)
                 for k in ("obs_spool_dir", "obs_role",
                           "obs_export_interval_s")}
    paddle.set_flags({"obs_spool_dir": spool, "obs_role": "parent",
                      "obs_export_interval_s": 0.2})
    from paddle_tpu.core import obs_hook
    had_tracer = obs_hook._tracer is not None
    obs_export.install_exporter()
    monitor.stat_reset()

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    url = f"http://127.0.0.1:{port}"
    state_file = os.path.join(workdir, "fleet_state")
    stop_file = os.path.join(workdir, "fleet_stop")

    sv = ServingSupervisor(
        _fleet_gen_entry, args=(port, state_file, stop_file),
        name="fleet", health_url=f"{url}/healthz",
        ready_poll_s=0.1, probe_timeout_s=2.0, ready_fail_budget=50,
        hang_deadline_s=300.0, startup_timeout_s=240.0, poll_s=0.1,
        backoff_s=0.1, backoff_max_s=0.5,
        crash_window_s=600.0, crash_budget=3,
        child_env={"JAX_PLATFORMS": "cpu",
                   "FLAGS_obs_export_interval_s": "0.2"},
        workdir=workdir)
    box = {}

    def run_sv():
        try:
            box["result"] = sv.run()
        except Exception as e:  # noqa: BLE001 - surfaced below
            box["error"] = e

    svt = threading.Thread(target=run_sv, daemon=True)
    svt.start()

    def wait_ready(deadline_s):
        deadline = time.monotonic() + deadline_s
        c = serving.Client(url, timeout=5, reconnect_backoff_s=0.05)
        while time.monotonic() < deadline:
            try:
                if c.healthz().get("ready"):
                    return True
            except Exception:  # noqa: BLE001 - replica not up yet
                pass
            time.sleep(0.1)
        return False

    tid = "fleetgate"
    ok_counts = []
    b_stop = threading.Event()

    def traffic():
        c = serving.Client(url, timeout=10, reconnect_backoff_s=0.1,
                           trace_id=tid)
        while not b_stop.is_set():
            try:
                toks = c.generate([3, 5], max_new_tokens=3)
                ok_counts.append(len(toks))
            except Exception:  # noqa: BLE001 - outage window
                pass
            time.sleep(0.05)

    try:
        if not wait_ready(240.0):
            return _fleet_report(["supervised replica never became "
                                  "ready"], verbose)
        tt = threading.Thread(target=traffic, daemon=True)
        tt.start()
        deadline = time.monotonic() + 120
        while monitor.get_stat("supervisor.serving.restarts") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        if monitor.get_stat("supervisor.serving.restarts") < 1:
            b_stop.set()
            return _fleet_report(["replica crash never triggered a "
                                  "supervised restart"], verbose)
        if not wait_ready(240.0):
            b_stop.set()
            return _fleet_report(["restarted replica never became "
                                  "ready again"], verbose)
        # at least one traced request must land on the NEW incarnation
        pre = len(ok_counts)
        deadline = time.monotonic() + 60
        while len(ok_counts) <= pre and time.monotonic() < deadline:
            time.sleep(0.1)
        b_stop.set()
        tt.join(30)
        if len(ok_counts) <= pre:
            problems.append("no /generate succeeded after the restart")
        with open(stop_file, "w") as f:
            f.write("1")
        svt.join(60)
        if "error" in box:
            problems.append(f"supervisor errored: {box['error']}")

        # -- spool: parent + BOTH child incarnations ----------------------
        exp = obs_export.get_exporter()
        if exp is not None:
            exp.flush()
        procs = obs_fleet.read_spool(spool)
        roles = {p["role"] for p in procs}
        for want in ("parent", "fleet-a0", "fleet-a1"):
            if want not in roles:
                problems.append(f"spool lacks a record for {want!r} "
                                f"(roles: {sorted(roles)})")
        corrupt = sum(p["corrupt"] for p in procs)
        if corrupt:
            problems.append(f"{corrupt} corrupt spool document(s)")

        # -- merged chrome trace: named aligned lanes + restart reason ----
        merged = obs_fleet.merged_chrome_trace(spool)
        evs = merged.get("traceEvents") or []
        lanes = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        for want in ("parent", "fleet-a0", "fleet-a1"):
            if not any(ln.startswith(want + "-") for ln in lanes):
                problems.append(f"merged trace lacks a lane for "
                                f"{want!r} (lanes: {sorted(lanes)})")
        restarts = [e for e in evs if e.get("name") == "restart"
                    and "crash" in str((e.get("args") or {})
                                       .get("reason", ""))]
        if not restarts:
            problems.append("merged trace lacks the supervisor restart "
                            "event with the crash reason")
        if any(e.get("ts", 0) < 0 for e in evs):
            problems.append("merged trace has negative timestamps "
                            "(lane alignment broke)")

        # -- fleet Prometheus: every sample proc-labelled -----------------
        text = obs_fleet.fleet_prometheus_text(spool)
        bad = [ln for ln in text.splitlines()
               if ln and not ln.startswith("#") and 'proc="' not in ln]
        if bad:
            problems.append(f"fleet Prometheus samples without a proc "
                            f"label: {bad[:3]}")

        # -- the pinned trace is ONE component across the process hop -----
        asm = obs_fleet.assemble_trace(
            obs_fleet._merge_self(list(procs)), tid)
        if not asm["connected"]:
            problems.append(f"distributed trace not connected: {asm}")
        if len(asm["pids"]) < 2:
            problems.append(f"distributed trace never crossed a "
                            f"process boundary: pids={asm['pids']}")
    finally:
        b_stop.set()
        with open(stop_file, "w") as f:
            f.write("1")
        sv.stop()
        svt.join(60)
        obs_export.uninstall_exporter()
        if not had_tracer:          # install_exporter enabled it for us
            from paddle_tpu import observability as _obs
            _obs.disable()
        paddle.set_flags(old_flags)
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)

    if verbose and not problems:
        print(f"fleet gate: {len(ok_counts)} traced generates, "
              f"restarts={monitor.get_stat('supervisor.serving.restarts')}, "
              f"procs={sorted(roles)}, trace pids={asm['pids']}")
    return _fleet_report(problems, verbose)


def _fleet_report(problems, verbose):
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    if not problems:
        print("chaos fleet: parent + both incarnations spooled, lanes "
              "aligned, restart reason visible, pinned /generate trace "
              "connected across the replica restart")
    return 1 if problems else 0
