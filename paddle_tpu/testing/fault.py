"""Deterministic fault injection.

The recovery paths this repo carries (fs retries, checkpoint fallback,
DataLoader worker respawn — reference: framework/io/fs.cc retries,
incubate/checkpoint/auto_checkpoint.py, fluid/reader.py SIGCHLD handler)
are worthless untested, and none of their failure modes occur naturally
on a developer box.  This module makes faults happen on demand:

* Instrumented code calls :func:`point` at named sites::

      fault.point("fs.open_write", path)

  Disarmed (the default), ``point`` is one module-bool check and a
  return — no rule matching, no RNG, no stat writes.

* Tests arm rules programmatically (:func:`arm` / :func:`inject`) or
  operators arm them process-wide through ``FLAGS_fault_spec``::

      FLAGS_fault_spec="fs.shell_run:p=0.3,count=2,exc=TransientFSError;\
mp.worker_batch:count=1,action=exit,code=43"

  Rule grammar: ``point_glob[:key=val[,key=val]*]`` joined by ``;``.
  Keys: ``p`` (fire probability, default 1), ``count`` (max fires,
  default unlimited), ``after`` (skip the first N matching hits),
  ``exc`` (exception class name, default :class:`FaultInjected`),
  ``msg`` (message override), ``match`` (substring that must appear in
  the point's detail args), ``action`` (``raise`` | ``exit`` |
  ``sleep``), ``code`` (exit status for ``action=exit``), ``secs``
  (wedge duration for ``action=sleep`` — the point blocks in
  ``time.sleep`` and then *returns*, so a short ``secs`` is a latency
  injection and a long one is a real hang only a supervisor's watchdog
  can clear), ``respawn`` (1 = keep the rule armed in *respawned*
  DataLoader workers; default 0 = kill-once).

* The RNG driving ``p`` is seeded (``seed=`` / ``FLAGS_fault_seed``) so
  a chaos run replays exactly.

Every fire increments ``monitor`` stat ``fault.fired.<point>`` so tests
can assert *which* recovery path ran.  Worker processes don't share the
parent's arm state: the DataLoader pool ships :func:`spec_for_children`
to each worker, which re-arms via :func:`arm`.
"""
from __future__ import annotations

import builtins
import fnmatch
import os
import random
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

__all__ = ["FaultInjected", "Rule", "arm", "disarm", "inject", "is_armed",
           "point", "fire_count", "spec_for_children", "arm_from_flags"]


class FaultInjected(RuntimeError):
    """Default exception raised by a fired injection point."""


# Exception names resolvable in specs without creating import cycles
# (fs imports this module, so this module must not import fs at top).
_EXC_HOMES = {
    "FaultInjected": (__name__, "FaultInjected"),
    "TransientFSError": ("paddle_tpu.utils.fs", "TransientFSError"),
    "PermanentFSError": ("paddle_tpu.utils.fs", "PermanentFSError"),
    "CheckpointError": ("paddle_tpu.utils.checkpoint", "CheckpointError"),
}


def _resolve_exc(name: Union[str, type]) -> type:
    if isinstance(name, type):
        return name
    if name in _EXC_HOMES:
        mod, attr = _EXC_HOMES[name]
        import importlib
        return getattr(importlib.import_module(mod), attr)
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(f"fault spec: unknown exception class '{name}'")


@dataclass
class Rule:
    pattern: str
    prob: float = 1.0
    count: Optional[int] = None      # max fires; None = unlimited
    after: int = 0                   # skip the first N matching hits
    exc: Union[str, type] = "FaultInjected"
    msg: str = ""
    match: str = ""                  # substring required in detail args
    action: str = "raise"            # raise | exit | sleep
    code: int = 43                   # exit status for action=exit
    secs: float = 60.0               # wedge duration for action=sleep
    respawn: bool = False            # survive into respawned workers
    hits: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    def to_spec(self) -> str:
        kv = []
        if self.prob != 1.0:
            kv.append(f"p={self.prob}")
        if self.count is not None:
            kv.append(f"count={self.count}")
        if self.after:
            kv.append(f"after={self.after}")
        exc_name = self.exc if isinstance(self.exc, str) else \
            self.exc.__name__
        if exc_name != "FaultInjected":
            kv.append(f"exc={exc_name}")
        if self.msg:
            kv.append(f"msg={self.msg}")
        if self.match:
            kv.append(f"match={self.match}")
        if self.action != "raise":
            kv.append(f"action={self.action}")
        if self.code != 43:
            kv.append(f"code={self.code}")
        if self.secs != 60.0:
            kv.append(f"secs={self.secs}")
        if self.respawn:
            kv.append("respawn=1")
        return self.pattern + (":" + ",".join(kv) if kv else "")


def parse_spec(spec: str) -> List[Rule]:
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            pattern, _, kvs = part.partition(":")
            kw = {}
            for item in kvs.split(","):
                if not item.strip():
                    continue
                k, _, v = item.partition("=")
                k = k.strip()
                v = v.strip()
                if k == "p":
                    kw["prob"] = float(v)
                elif k == "secs":
                    kw["secs"] = float(v)
                elif k in ("count", "after", "code"):
                    kw[k] = int(v)
                elif k == "respawn":
                    kw["respawn"] = v not in ("0", "false", "")
                elif k in ("exc", "msg", "match", "action"):
                    kw[k] = v
                else:
                    raise ValueError(f"fault spec: unknown key '{k}' in "
                                     f"'{part}'")
            rules.append(Rule(pattern.strip(), **kw))
        else:
            rules.append(Rule(part))
    return rules


_lock = threading.Lock()
_armed = False          # read without the lock on the hot path
_rules: List[Rule] = []
_seed = 0
_rng = random.Random(0)


def arm(rules: Union[str, Sequence[Rule]], seed: int = 0) -> None:
    """Arm the injector with a spec string or a list of :class:`Rule`."""
    global _armed, _rules, _rng, _seed
    with _lock:
        _rules = parse_spec(rules) if isinstance(rules, str) else \
            list(rules)
        _seed = int(seed)
        _rng = random.Random(_seed)
        _armed = bool(_rules)


def disarm() -> None:
    global _armed, _rules
    with _lock:
        _armed = False
        _rules = []


def is_armed() -> bool:
    return _armed


class inject:
    """``with fault.inject("fs.open_write:count=1"):`` — scoped arming
    that restores the previous arm state on exit (exception or not)."""

    def __init__(self, rules: Union[str, Sequence[Rule]], seed: int = 0):
        self._rules = rules
        self._seed = seed

    def __enter__(self):
        self._prev = (_armed, list(_rules), _seed)
        arm(self._rules, self._seed)
        return self

    def __exit__(self, *exc_info):
        was_armed, rules, seed = self._prev
        if was_armed:
            arm(rules, seed)
        else:
            disarm()
        return False


def point(name: str, *detail) -> None:
    """A named injection site.  No-op unless the injector is armed."""
    if not _armed:
        return
    _hit(name, detail)


def _hit(name: str, detail: Tuple) -> None:
    with _lock:
        rule = None
        for r in _rules:
            if not fnmatch.fnmatchcase(name, r.pattern):
                continue
            if r.match and not any(r.match in str(d) for d in detail):
                continue
            r.hits += 1
            if r.hits <= r.after:
                continue
            if r.count is not None and r.fires >= r.count:
                continue
            if r.prob < 1.0 and _rng.random() >= r.prob:
                continue
            r.fires += 1
            rule = r
            break
        if rule is None:
            return
    from ..utils import monitor
    monitor.stat_add(f"fault.fired.{name}")
    from ..core import obs_hook
    trc = obs_hook._tracer
    if trc is not None:
        # the fire lands on the trace BEFORE the raise/exit, so a crash
        # flight dump always shows the injected fault that caused it
        trc.emit("fault", name,
                 args={"detail": [str(d) for d in detail],
                       "action": rule.action})
    msg = rule.msg or (f"injected fault at '{name}'"
                       + (f" ({', '.join(map(str, detail))})"
                          if detail else ""))
    if rule.action == "exit":
        os._exit(rule.code)
    if rule.action == "sleep":
        # a real wedge: the calling thread blocks right here.  SIGTERM
        # handlers run but the sleep resumes (PEP 475), so only SIGKILL
        # — or the sleep expiring — unwedges the process, which is
        # exactly the failure mode a hang watchdog exists to detect.
        import time
        time.sleep(rule.secs)
        return
    raise _resolve_exc(rule.exc)(msg)


def fire_count(name: Optional[str] = None) -> int:
    """Total fires, or fires of rules whose pattern matches ``name``."""
    with _lock:
        if name is None:
            return sum(r.fires for r in _rules)
        return sum(r.fires for r in _rules
                   if fnmatch.fnmatchcase(name, r.pattern))


def spec_for_children(respawn: bool = False) -> Optional[Tuple[str, int]]:
    """Serialized ``(spec, seed)`` to re-arm a worker process, or None.

    ``respawn=True`` keeps only rules marked ``respawn=1`` — by default a
    worker-kill rule fires in the first generation of workers and the
    respawned replacements run clean (kill-once chaos semantics).
    """
    with _lock:
        if not _armed:
            return None
        rules = [r for r in _rules if r.respawn] if respawn else _rules
        if not rules:
            return None
        return ";".join(r.to_spec() for r in rules), _seed


def arm_from_flags() -> bool:
    """Arm from ``FLAGS_fault_spec`` / ``FLAGS_fault_seed`` (set via
    ``paddle_tpu.set_flags`` or the environment).  Returns armed state."""
    from ..core import flags
    spec = flags.get_flag("fault_spec")
    if spec:
        arm(spec, seed=flags.get_flag("fault_seed"))
    return _armed


# Environment-armed chaos (FLAGS_fault_spec=... python train.py) must work
# before anyone imports core.flags — read the env directly at import.
_env_spec = os.environ.get("FLAGS_fault_spec")
if _env_spec:
    arm(_env_spec, seed=int(os.environ.get("FLAGS_fault_seed", "0")))
