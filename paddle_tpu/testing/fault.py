"""Deterministic fault injection.

The recovery paths this repo carries (fs retries, checkpoint fallback,
DataLoader worker respawn — reference: framework/io/fs.cc retries,
incubate/checkpoint/auto_checkpoint.py, fluid/reader.py SIGCHLD handler)
are worthless untested, and none of their failure modes occur naturally
on a developer box.  This module makes faults happen on demand:

* Instrumented code calls :func:`point` at named sites::

      fault.point("fs.open_write", path)

  Disarmed (the default), ``point`` is one module-bool check and a
  return — no rule matching, no RNG, no stat writes.

* Tests arm rules programmatically (:func:`arm` / :func:`inject`) or
  operators arm them process-wide through ``FLAGS_fault_spec``::

      FLAGS_fault_spec="fs.shell_run:p=0.3,count=2,exc=TransientFSError;\
mp.worker_batch:count=1,action=exit,code=43"

  Rule grammar: ``point_glob[:key=val[,key=val]*]`` joined by ``;``.
  Keys: ``p`` (fire probability, default 1), ``count`` (max fires,
  default unlimited), ``after`` (skip the first N matching hits),
  ``exc`` (exception class name, default :class:`FaultInjected`),
  ``msg`` (message override), ``match`` (substring that must appear in
  the point's detail args), ``action`` (``raise`` | ``exit`` |
  ``sleep`` | ``corrupt``), ``code`` (exit status for ``action=exit``),
  ``secs`` (wedge duration for ``action=sleep`` — the point blocks in
  ``time.sleep`` and then *returns*, so a short ``secs`` is a latency
  injection and a long one is a real hang only a supervisor's watchdog
  can clear), ``respawn`` (1 = keep the rule armed in *respawned*
  DataLoader workers; default 0 = kill-once).

* **Data corruption** (``action=corrupt``) — instead of raising, the
  point *poisons the payload* flowing through it: ``mode`` picks the
  corruption (``nan`` | ``inf`` | ``bitflip``), ``n`` how many leading
  elements are hit (default 1), and ``tensor`` a glob that must match
  the tensor's label (e.g. ``tensor=*scales*`` corrupts only the int8
  block scales of the quantized wire payload).  Corruption points come
  in two kinds:

  - **host points** (``dataloader.batch``) call :func:`corrupt_host`
    on the emitted numpy/Tensor tree — full ``p``/``count``/``after``/
    ``match`` semantics, counted as ``fault.fired.<point>``;
  - **in-graph points** (``executor.grads``, ``grad_comm.wire``) are
    lowered *into the compiled train step* by :func:`corrupt_in_graph`:
    the rule's ``after``/``count`` become a step window
    (``after < step <= after + count``) and ``p`` a per-step Bernoulli
    draw keyed on the fault seed, selected with ``jnp.where`` — zero
    host syncs, replayable, 0-recompile after warmup.  The graph is
    built from the arm state at *compile* time: arm corrupt rules
    before the first run (arming later does nothing until a
    recompile), and note that a rule matching several sites (several
    buckets, q + scales) corrupts each matching site in its window —
    use ``tensor=`` to single one out.  The host mirrors the
    deterministic schedule (:func:`mirror_graph_fires`) so
    ``fault.fired.<point>`` stats stay truthful for in-graph fires.

* The RNG driving ``p`` is seeded (``seed=`` / ``FLAGS_fault_seed``) so
  a chaos run replays exactly.

Every fire increments ``monitor`` stat ``fault.fired.<point>`` so tests
can assert *which* recovery path ran.  Worker processes don't share the
parent's arm state: the DataLoader pool ships :func:`spec_for_children`
to each worker, which re-arms via :func:`arm`.
"""
from __future__ import annotations

import builtins
import fnmatch
import os
import random
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

__all__ = ["FaultInjected", "Rule", "arm", "disarm", "inject", "is_armed",
           "point", "fire_count", "spec_for_children", "arm_from_flags",
           "corrupt_host", "corrupt_in_graph", "corrupt_rules",
           "mirror_graph_fires"]


class FaultInjected(RuntimeError):
    """Default exception raised by a fired injection point."""


# Exception names resolvable in specs without creating import cycles
# (fs imports this module, so this module must not import fs at top).
_EXC_HOMES = {
    "FaultInjected": (__name__, "FaultInjected"),
    "TransientFSError": ("paddle_tpu.utils.fs", "TransientFSError"),
    "PermanentFSError": ("paddle_tpu.utils.fs", "PermanentFSError"),
    "CheckpointError": ("paddle_tpu.utils.checkpoint", "CheckpointError"),
}


def _resolve_exc(name: Union[str, type]) -> type:
    if isinstance(name, type):
        return name
    if name in _EXC_HOMES:
        mod, attr = _EXC_HOMES[name]
        import importlib
        return getattr(importlib.import_module(mod), attr)
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(f"fault spec: unknown exception class '{name}'")


@dataclass
class Rule:
    pattern: str
    prob: float = 1.0
    count: Optional[int] = None      # max fires; None = unlimited
    after: int = 0                   # skip the first N matching hits
    exc: Union[str, type] = "FaultInjected"
    msg: str = ""
    match: str = ""                  # substring required in detail args
    action: str = "raise"            # raise | exit | sleep | corrupt
    code: int = 43                   # exit status for action=exit
    secs: float = 60.0               # wedge duration for action=sleep
    respawn: bool = False            # survive into respawned workers
    mode: str = "nan"                # corrupt: nan | inf | bitflip
    n: int = 1                       # corrupt: leading elements poisoned
    tensor: str = ""                 # corrupt: glob on the tensor label
    hits: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    def to_spec(self) -> str:
        kv = []
        if self.prob != 1.0:
            kv.append(f"p={self.prob}")
        if self.count is not None:
            kv.append(f"count={self.count}")
        if self.after:
            kv.append(f"after={self.after}")
        exc_name = self.exc if isinstance(self.exc, str) else \
            self.exc.__name__
        if exc_name != "FaultInjected":
            kv.append(f"exc={exc_name}")
        if self.msg:
            kv.append(f"msg={self.msg}")
        if self.match:
            kv.append(f"match={self.match}")
        if self.action != "raise":
            kv.append(f"action={self.action}")
        if self.code != 43:
            kv.append(f"code={self.code}")
        if self.secs != 60.0:
            kv.append(f"secs={self.secs}")
        if self.respawn:
            kv.append("respawn=1")
        if self.mode != "nan":
            kv.append(f"mode={self.mode}")
        if self.n != 1:
            kv.append(f"n={self.n}")
        if self.tensor:
            kv.append(f"tensor={self.tensor}")
        return self.pattern + (":" + ",".join(kv) if kv else "")


def parse_spec(spec: str) -> List[Rule]:
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            pattern, _, kvs = part.partition(":")
            kw = {}
            for item in kvs.split(","):
                if not item.strip():
                    continue
                k, _, v = item.partition("=")
                k = k.strip()
                v = v.strip()
                if k == "p":
                    kw["prob"] = float(v)
                elif k == "secs":
                    kw["secs"] = float(v)
                elif k in ("count", "after", "code", "n"):
                    kw[k] = int(v)
                elif k == "respawn":
                    kw["respawn"] = v not in ("0", "false", "")
                elif k in ("exc", "msg", "match", "action", "mode",
                           "tensor"):
                    kw[k] = v
                else:
                    raise ValueError(f"fault spec: unknown key '{k}' in "
                                     f"'{part}'")
            rule = Rule(pattern.strip(), **kw)
            if rule.action == "corrupt" and rule.mode not in (
                    "nan", "inf", "bitflip"):
                raise ValueError(f"fault spec: corrupt mode "
                                 f"'{rule.mode}' in '{part}' (want "
                                 f"nan | inf | bitflip)")
            rules.append(rule)
        else:
            rules.append(Rule(part))
    return rules


_lock = threading.Lock()
_armed = False          # read without the lock on the hot path
_rules: List[Rule] = []
_seed = 0
_rng = random.Random(0)


def arm(rules: Union[str, Sequence[Rule]], seed: int = 0) -> None:
    """Arm the injector with a spec string or a list of :class:`Rule`."""
    global _armed, _rules, _rng, _seed
    with _lock:
        _rules = parse_spec(rules) if isinstance(rules, str) else \
            list(rules)
        _seed = int(seed)
        _rng = random.Random(_seed)
        _armed = bool(_rules)


def disarm() -> None:
    global _armed, _rules
    with _lock:
        _armed = False
        _rules = []


def is_armed() -> bool:
    return _armed


class inject:
    """``with fault.inject("fs.open_write:count=1"):`` — scoped arming
    that restores the previous arm state on exit (exception or not)."""

    def __init__(self, rules: Union[str, Sequence[Rule]], seed: int = 0):
        self._rules = rules
        self._seed = seed

    def __enter__(self):
        self._prev = (_armed, list(_rules), _seed)
        arm(self._rules, self._seed)
        return self

    def __exit__(self, *exc_info):
        was_armed, rules, seed = self._prev
        if was_armed:
            arm(rules, seed)
        else:
            disarm()
        return False


def point(name: str, *detail) -> None:
    """A named injection site.  No-op unless the injector is armed."""
    if not _armed:
        return
    _hit(name, detail)


def _hit(name: str, detail: Tuple) -> None:
    with _lock:
        rule = None
        for r in _rules:
            if r.action == "corrupt":
                continue   # corrupt rules fire only at corruption points
            if not fnmatch.fnmatchcase(name, r.pattern):
                continue
            if r.match and not any(r.match in str(d) for d in detail):
                continue
            r.hits += 1
            if r.hits <= r.after:
                continue
            if r.count is not None and r.fires >= r.count:
                continue
            if r.prob < 1.0 and _rng.random() >= r.prob:
                continue
            r.fires += 1
            rule = r
            break
        if rule is None:
            return
    from ..utils import monitor
    monitor.stat_add(f"fault.fired.{name}")
    from ..core import obs_hook
    trc = obs_hook._tracer
    if trc is not None:
        # the fire lands on the trace BEFORE the raise/exit, so a crash
        # flight dump always shows the injected fault that caused it
        trc.emit("fault", name,
                 args={"detail": [str(d) for d in detail],
                       "action": rule.action})
    msg = rule.msg or (f"injected fault at '{name}'"
                       + (f" ({', '.join(map(str, detail))})"
                          if detail else ""))
    if rule.action == "exit":
        os._exit(rule.code)
    if rule.action == "sleep":
        # a real wedge: the calling thread blocks right here.  SIGTERM
        # handlers run but the sleep resumes (PEP 475), so only SIGKILL
        # — or the sleep expiring — unwedges the process, which is
        # exactly the failure mode a hang watchdog exists to detect.
        import time
        time.sleep(rule.secs)
        return
    raise _resolve_exc(rule.exc)(msg)


def fire_count(name: Optional[str] = None) -> int:
    """Total fires, or fires of rules whose pattern matches ``name``."""
    with _lock:
        if name is None:
            return sum(r.fires for r in _rules)
        return sum(r.fires for r in _rules
                   if fnmatch.fnmatchcase(name, r.pattern))


def spec_for_children(respawn: bool = False) -> Optional[Tuple[str, int]]:
    """Serialized ``(spec, seed)`` to re-arm a worker process, or None.

    ``respawn=True`` keeps only rules marked ``respawn=1`` — by default a
    worker-kill rule fires in the first generation of workers and the
    respawned replacements run clean (kill-once chaos semantics).
    """
    with _lock:
        if not _armed:
            return None
        rules = [r for r in _rules if r.respawn] if respawn else _rules
        if not rules:
            return None
        return ";".join(r.to_spec() for r in rules), _seed


def arm_from_flags() -> bool:
    """Arm from ``FLAGS_fault_spec`` / ``FLAGS_fault_seed`` (set via
    ``paddle_tpu.set_flags`` or the environment).  Returns armed state."""
    from ..core import flags
    spec = flags.get_flag("fault_spec")
    if spec:
        arm(spec, seed=flags.get_flag("fault_seed"))
    return _armed


# ---------------------------------------------------------------------------
# Data corruption (action=corrupt): host trees and in-graph tensors
# ---------------------------------------------------------------------------

def _corrupt_np(a, mode: str, n: int):
    """Poison the first ``n`` elements of a numpy array (returns a
    copy; the caller's array is never mutated)."""
    import numpy as np
    a = np.array(a, copy=True)          # C-contiguous copy
    flat = a.reshape(-1)                # view into the copy
    k = max(1, min(int(n), flat.shape[0]))
    if mode in ("nan", "inf") and not np.issubdtype(a.dtype,
                                                    np.floating):
        mode = "bitflip"     # int payloads have no NaN — flip bits
    if mode == "nan":
        flat[:k] = np.nan
    elif mode == "inf":
        flat[:k] = np.inf
    else:
        nbits = 8 * a.dtype.itemsize
        u = flat[:k].view(np.dtype(f"u{a.dtype.itemsize}"))
        # flip a high bit (exponent territory for floats): the poison
        # stays finite but lands far outside the healthy value range
        u ^= np.asarray(1 << (nbits - 2), dtype=u.dtype)
    return a


def corrupt_host(name: str, tree, *detail, tensor: str = ""):
    """Apply any armed ``action=corrupt`` rule matching ``name`` (and
    ``tensor``/``match``) to a host-side batch/array tree, honoring the
    full ``p``/``count``/``after`` hit accounting.  numpy, Tensor, and
    nested tuple/list/dict leaves are supported; the corrupted tree is
    a copy — the caller's original arrays are never mutated.  No-op
    (identity, zero cost beyond one bool check) when disarmed."""
    if not _armed:
        return tree
    with _lock:
        rule = None
        for r in _rules:
            if r.action != "corrupt":
                continue
            if not fnmatch.fnmatchcase(name, r.pattern):
                continue
            if r.tensor and not fnmatch.fnmatchcase(tensor, r.tensor):
                continue
            if r.match and not any(r.match in str(d) for d in detail):
                continue
            r.hits += 1
            if r.hits <= r.after:
                continue
            if r.count is not None and r.fires >= r.count:
                continue
            if r.prob < 1.0 and _rng.random() >= r.prob:
                continue
            r.fires += 1
            rule = r
            break
    if rule is None:
        return tree
    from ..utils import monitor
    monitor.stat_add(f"fault.fired.{name}")
    from ..core import obs_hook
    trc = obs_hook._tracer
    if trc is not None:
        trc.emit("fault", name,
                 args={"detail": [str(d) for d in detail],
                       "action": "corrupt", "mode": rule.mode})

    def walk(x):
        from ..core.tensor import Tensor
        import numpy as np
        if isinstance(x, Tensor):
            return Tensor(_corrupt_np(np.asarray(x.data), rule.mode,
                                      rule.n))
        if isinstance(x, np.ndarray):
            return _corrupt_np(x, rule.mode, rule.n)
        if isinstance(x, (list, tuple)):
            return type(x)(walk(v) for v in x)
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x
    return walk(tree)


def corrupt_rules(name: str, tensor: str = "") -> List[Rule]:
    """Armed ``action=corrupt`` rules matching an in-graph corruption
    site — consulted at trace/compile time (no hit accounting: in-graph
    rules fire on their deterministic step window instead)."""
    if not _armed:
        return []
    with _lock:
        return [r for r in _rules
                if r.action == "corrupt"
                and fnmatch.fnmatchcase(name, r.pattern)
                and (not r.tensor
                     or fnmatch.fnmatchcase(tensor, r.tensor))]


def _site_key(name: str, tensor: str, rule: Rule):
    """Deterministic PRNG key for a (site, rule) pair's p-draws — the
    in-graph lowering and the host mirror derive the identical key, so
    probabilistic in-graph fires replay and the mirror never lies."""
    import zlib
    import jax
    base = zlib.crc32(f"{name}|{tensor}|{rule.to_spec()}".encode())
    return jax.random.PRNGKey((_seed ^ base) & 0x7fffffff)


def _window_pred(rule: Rule, step):
    """In-graph fire predicate of a corrupt rule at a (traced) 1-based
    step counter: ``after < step <= after + count``, times a Bernoulli
    draw when ``p < 1`` (``count`` then bounds the window, not the
    realized fires)."""
    import jax.numpy as jnp
    fire = step > rule.after
    if rule.count is not None:
        fire = jnp.logical_and(fire, step <= rule.after + rule.count)
    return fire


def corrupt_in_graph(name: str, x, step, tensor: str = ""):
    """In-graph corruption site: returns ``x``, possibly rewritten to
    ``jnp.where(fire(step), corrupted(x), x)`` when an armed corrupt
    rule matches at trace time.  ``step`` is the executable's (traced)
    1-based step counter.  With nothing armed this is a pure identity —
    the compiled graph is byte-identical to an un-instrumented one."""
    rules = corrupt_rules(name, tensor)
    if not rules:
        return x
    import jax
    import jax.numpy as jnp
    for rule in rules:
        fire = _window_pred(rule, step)
        if rule.prob < 1.0:
            key = jax.random.fold_in(_site_key(name, tensor, rule),
                                     step)
            fire = jnp.logical_and(
                fire, jax.random.uniform(key) < rule.prob)
        flat = x.reshape(-1)
        k = max(1, min(int(rule.n), int(flat.shape[0])))
        mode = rule.mode
        if mode in ("nan", "inf") and not jnp.issubdtype(
                x.dtype, jnp.floating):
            mode = "bitflip"
        if mode == "nan":
            bad = flat.at[:k].set(jnp.nan)
        elif mode == "inf":
            bad = flat.at[:k].set(jnp.inf)
        else:
            nbits = 8 * x.dtype.itemsize
            u = jax.lax.bitcast_convert_type(
                flat[:k], jnp.dtype(f"uint{nbits}"))
            # flip a high bit: detectable as a huge value / spike even
            # when the poisoned payload stays finite
            u = u ^ jnp.asarray(1 << (nbits - 2), u.dtype)
            bad = flat.at[:k].set(
                jax.lax.bitcast_convert_type(u, x.dtype))
        x = jnp.where(fire, bad.reshape(x.shape), x)
    return x


def graph_corrupt_sites(points) -> List[tuple]:
    """``[(point, tensor_label, rule)]`` for every in-graph site with an
    armed corrupt rule — computed by the Executor at compile time (the
    same arm state the trace sees) and attached to the executable so
    :func:`mirror_graph_fires` can keep host-side fire accounting."""
    out = []
    for name, tensor in points:
        for r in corrupt_rules(name, tensor):
            out.append((name, tensor, r))
    return out


def mirror_graph_fires(sites, step: int) -> None:
    """Host mirror of the in-graph fire schedule: for each compiled
    corruption site, evaluate the identical window/Bernoulli predicate
    at the (concrete) step and bump ``fault.fired.<point>`` stats +
    rule fire counts — in-graph fires never touch the host, so this is
    what keeps ``fire_count()`` and the monitor truthful."""
    if not sites:
        return
    for name, tensor, rule in sites:
        if step <= rule.after:
            continue
        if rule.count is not None and step > rule.after + rule.count:
            continue
        if rule.prob < 1.0:
            import jax
            key = jax.random.fold_in(_site_key(name, tensor, rule),
                                     step)
            if not bool(jax.random.uniform(key) < rule.prob):
                continue
        with _lock:
            rule.fires += 1
        from ..utils import monitor
        monitor.stat_add(f"fault.fired.{name}")
        from ..core import obs_hook
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("fault", name,
                     args={"detail": [tensor, f"step={step}"],
                           "action": "corrupt", "mode": rule.mode,
                           "in_graph": True})


# Environment-armed chaos (FLAGS_fault_spec=... python train.py) must work
# before anyone imports core.flags — read the env directly at import.
_env_spec = os.environ.get("FLAGS_fault_spec")
if _env_spec:
    arm(_env_spec, seed=int(os.environ.get("FLAGS_fault_seed", "0")))
