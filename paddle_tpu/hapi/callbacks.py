"""Training callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    # always invoked by Model.fit (finally:), even when training raises
    # — release process-wide resources (signal handlers, files) here
    def on_train_cleanup(self): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def _dispatch(*args, **kw):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kw)
            return _dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """reference: hapi/callbacks.py ProgBarLogger."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {np.asarray(v).item() if hasattr(v, 'item') else v:.4f}"
                if isinstance(v, (int, float, np.floating)) else f"{k}: {v}"
                for k, v in logs.items())
            print(f"step {step + 1}/{self.steps or '?'} - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            items = " - ".join(f"{k}: {v}" for k, v in logs.items())
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class Checkpoint(Callback):
    """Fault-tolerant checkpointing for ``Model.fit`` — the
    utils.checkpoint robustness knobs surfaced as a callback.

    Every ``save_freq`` epochs the network (and optimizer, when
    prepared) snapshot into ``save_dir`` with per-file sha256 digests,
    rotated to the last ``keep_checkpoint_max`` snapshots.  On train
    begin the newest snapshot that VERIFIES is restored (corrupt ones
    fall back to the previous intact snapshot), so a preempted
    ``fit()`` continues from published weights instead of from scratch.
    While training, SIGTERM — the cloud-TPU preemption notice —
    requests a snapshot at the next epoch boundary and then stops
    training cleanly (``model.stop_training``); ``self.preempted``
    records that this happened.  Note: ``fit`` restarts its epoch
    counter — for exact epoch-resume loops use
    ``utils.checkpoint.TrainEpochRange``."""

    def __init__(self, save_dir, save_freq=1, keep_checkpoint_max=None,
                 verify=True, restore=True, handle_preemption=True):
        super().__init__()
        self.save_dir = save_dir
        self.save_freq = max(1, int(save_freq))
        self.keep_checkpoint_max = keep_checkpoint_max
        self.verify = verify
        self.restore = restore
        self.handle_preemption = handle_preemption
        self.preempted = False
        self.last_restored_epoch = None
        self._store = None
        self._restore_handler = None

    def _objects(self):
        objs = {"model": self.model.network}
        if getattr(self.model, "_optimizer", None) is not None:
            objs["optimizer"] = self.model._optimizer
        return objs

    def _on_preempt(self):
        self.preempted = True

    def on_train_begin(self, logs=None):
        from ..utils.checkpoint import (SnapshotStore,
                                        install_preemption_handler)
        self._store = SnapshotStore(self.save_dir,
                                    keep_max=self.keep_checkpoint_max,
                                    verify=self.verify)
        self.preempted = False
        if self.restore:
            # restore() returns 0 when no checkpoint is published yet
            resumed = self._store.restore(self._objects())
            self.last_restored_epoch = resumed - 1 if resumed else None
        if self.handle_preemption:
            self._restore_handler = \
                install_preemption_handler(self._on_preempt)

    def on_epoch_end(self, epoch, logs=None):
        if self.preempted or (epoch + 1) % self.save_freq == 0:
            self._store.save(epoch, self._objects())
        if self.preempted:
            from ..utils import monitor
            monitor.stat_add("checkpoint.preempt_saves")
            self.model.stop_training = True

    def on_train_cleanup(self):
        if self._restore_handler is not None:
            self._restore_handler()
            self._restore_handler = None

    def on_train_end(self, logs=None):
        self.on_train_cleanup()


class MetricsDump(Callback):
    """Append monitor-metrics snapshots (stats + histograms) as JSONL
    while ``Model.fit`` runs — the training-side feed of the unified
    metrics exporter (``observability.dump_metrics``).

    One line per ``save_freq`` epochs plus one at train end; each line
    is a full ``observability.metrics_snapshot`` tagged with the epoch.
    ``path`` defaults to ``FLAGS_metrics_dump_path``; when that flag is
    set, ``Model.fit`` attaches this callback automatically."""

    def __init__(self, path=None, save_freq=1):
        super().__init__()
        self.path = path
        self.save_freq = max(1, int(save_freq))

    def _dump(self, tag, extra=None):
        from ..core.flags import get_flag
        path = self.path or get_flag("metrics_dump_path")
        if not path:
            return
        from ..observability import dump_metrics
        dump_metrics(path, extra={"tag": tag, **(extra or {})})

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            self._dump("epoch_end", {"epoch": epoch})

    def on_train_end(self, logs=None):
        self._dump("train_end")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = None

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        cur = float(np.asarray(cur))
        improved = (self.best is None or
                    (cur < self.best - self.min_delta
                     if self.mode == "min"
                     else cur > self.best + self.min_delta))
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: hapi LRScheduler cb)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reduce lr when a monitored metric stops improving (reference:
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="min", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._best = None
        self._wait = 0
        self._cool = 0
        self._saw_eval = False

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == "min":
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    # check ONCE per epoch: eval logs when evaluation runs, else train
    # logs (the reference checks a single monitored stream)
    def on_eval_end(self, logs=None):
        self._saw_eval = True
        self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        if not self._saw_eval:
            self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        if self._cool > 0:
            self._cool -= 1
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                sched = getattr(opt, "_lr_scheduler", None)
                if sched is not None:
                    import warnings
                    warnings.warn(
                        "ReduceLROnPlateau callback skipped: the "
                        "optimizer drives an LRScheduler; use "
                        "optimizer.lr.ReduceOnPlateau as the scheduler "
                        "instead")
                else:
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if new < old:
                        opt._learning_rate = new
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old:g} -> "
                                  f"{new:g}")
            self._wait = 0
            self._cool = self.cooldown


class VisualDL(Callback):
    """Scalar logger (reference: hapi VisualDL callback).  The VisualDL
    writer is GPU-ecosystem tooling; here scalars append to a JSONL file
    readable by any dashboard."""

    def __init__(self, log_dir="./vdl_log"):
        super().__init__()
        import os
        os.makedirs(log_dir, exist_ok=True)
        self._path = os.path.join(log_dir, "scalars.jsonl")
        self._step = 0

    def _write(self, tag, logs):
        import json
        logs = logs or {}
        rows = []
        for k, v in logs.items():
            try:
                v = float(v[0] if isinstance(v, (list, tuple)) else v)
            except (TypeError, ValueError):
                continue
            rows.append({"tag": f"{tag}/{k}", "step": self._step,
                         "value": v})
        if rows:
            with open(self._path, "a") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    from ..core.flags import get_flag
    if get_flag("metrics_dump_path") and not any(
            isinstance(c, MetricsDump) for c in cbks):
        cbks.append(MetricsDump())
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl
