"""paddle_tpu.hapi — Keras-like high-level Model API
(reference: python/paddle/hapi/model.py:810 — Model.fit :1299, evaluate,
predict; dygraph+static adapters :263,:642).

TPU-first: `prepare()` compiles a fused TrainStep (forward+backward+update
in one XLA executable) — the role the reference's static-graph adapter
plays — while keeping the dygraph-style API."""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (Callback, EarlyStopping, LRScheduler,  # noqa
                        ModelCheckpoint, ProgBarLogger,
                        ReduceLROnPlateau, VisualDL)


def summary(net, input_size=None, dtypes=None):
    """paddle.summary parity: parameter count table."""
    rows = []
    total = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(list(shape)):<20}{n:>12,}")
    lines.append(f"Total params: {total:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": total}
