"""paddle_tpu.hapi — Keras-like high-level Model API
(reference: python/paddle/hapi/model.py:810 — Model.fit :1299, evaluate,
predict; dygraph+static adapters :263,:642).

TPU-first: `prepare()` compiles a fused TrainStep (forward+backward+update
in one XLA executable) — the role the reference's static-graph adapter
plays — while keeping the dygraph-style API."""
from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (Callback, Checkpoint, EarlyStopping,  # noqa
                        LRScheduler, ModelCheckpoint, ProgBarLogger,
                        ReduceLROnPlateau, VisualDL)


def summary(net, input_size=None, dtypes=None):
    """paddle.summary parity (reference: hapi/model_summary.py:summary):
    per-layer table with OUTPUT SHAPES (captured via forward hooks on a
    zero-input forward when ``input_size`` is given) and parameter
    counts, split into trainable / non-trainable totals."""
    import numpy as np

    from ..core import autograd
    from ..core.tensor import Tensor

    def _params_of(layer):
        n = t = 0
        for p in layer.parameters(include_sublayers=False):
            n += p.size
            if not p.stop_gradient:
                t += p.size
        return n, t

    out_shapes = {}
    if input_size is not None:
        sizes = (input_size if isinstance(input_size, list)
                 else [input_size])
        dts = dtypes if isinstance(dtypes, list) else [
            dtypes or "float32"] * len(sizes)
        feeds = [Tensor(np.zeros([d if d is not None and d > 0 else 1
                                  for d in s], np.dtype(dt)))
                 for s, dt in zip(sizes, dts)]
        handles = []

        def mk_hook(name):
            def hook(layer, inputs, outputs):
                o = outputs[0] if isinstance(outputs, (list, tuple)) \
                    else outputs
                if hasattr(o, "shape"):
                    out_shapes[name] = list(o.shape)
                return outputs
            return hook

        for name, sub in net.named_sublayers():
            handles.append(sub.register_forward_post_hook(mk_hook(name)))
        was = net.training
        net.eval()
        try:
            with autograd.no_grad():
                net(*feeds)
        finally:
            if was:
                net.train()
            for h in handles:
                h.remove()

    rows = []
    for name, sub in net.named_sublayers():
        n, _ = _params_of(sub)
        rows.append((f"{name} ({type(sub).__name__})",
                     str(out_shapes.get(name, "-")), n))
    root_n, _ = _params_of(net)
    if root_n or not rows:      # params registered directly on the root
        rows.insert(0, (f"({type(net).__name__})", "-", root_n))
    # totals from the deduped parameter set (shared/tied params count
    # once; per-row numbers above are per-layer attributions)
    seen = {}
    for _, p in net.named_parameters():
        seen[id(p)] = p
    total = sum(p.size for p in seen.values())
    trainable = sum(p.size for p in seen.values() if not p.stop_gradient)
    w0 = max(max((len(r[0]) for r in rows), default=10), 14) + 2
    w1 = max(max((len(r[1]) for r in rows), default=10), 14) + 2
    lines = ["-" * (w0 + w1 + 12),
             f"{'Layer (type)':<{w0}}{'Output Shape':<{w1}}{'Param #':>12}",
             "=" * (w0 + w1 + 12)]
    for name, shape, n in rows:
        lines.append(f"{name:<{w0}}{shape:<{w1}}{n:>12,}")
    lines += ["=" * (w0 + w1 + 12),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (w0 + w1 + 12)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
