"""paddle.Model (reference: python/paddle/hapi/model.py:810)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..jit.train_step import TrainStep
from ..metric import Metric
from .callbacks import config_callbacks


class Model:
    """High-level trainer: prepare → fit/evaluate/predict → save/load."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step: Optional[TrainStep] = None
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile=True):
        """reference: hapi/model.py:1640 — `jit_compile` is the adapter
        switch (the reference's dygraph/static duality :263/:642):
        True compiles one fused TrainStep; False runs the eager tape.
        ``loss`` may return a list/tuple of losses (multi-task heads);
        they are summed for the update and reported summed."""
        self._optimizer = optimizer
        self._loss = _wrap_loss(loss) if loss is not None else None
        metrics = metrics or []
        self._metrics = metrics if isinstance(metrics, list) else [metrics]
        self._jit = jit_compile
        if optimizer is not None and loss is not None and jit_compile:
            n_in = (len(self._inputs)
                    if isinstance(self._inputs, (list, tuple)) else 1)
            self._train_step = TrainStep(self.network, self._loss,
                                         optimizer, n_inputs=n_in)

    # -- data plumbing -----------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle, num_workers=0):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # assume iterable of batches

    def _split_batch(self, batch):
        """Split a loader batch into (inputs, labels): by the declared
        ``inputs=``/``labels=`` specs when given (multi-input models,
        hapi/model.py _update_inputs), else input*, label."""
        if isinstance(batch, (list, tuple)):
            if isinstance(self._inputs, (list, tuple)):
                k = len(self._inputs)
                return batch[:k], batch[k:]
            if len(batch) >= 2:
                return batch[:-1], batch[-1:]
            return batch, ()
        return (batch,), ()

    # -- training ----------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = (labels if isinstance(labels, (list, tuple))
                  else ([labels] if labels is not None else []))
        if self._train_step is not None:
            self._train_step.n_inputs = len(inputs)
            loss = self._train_step(*inputs, *labels)
        else:
            out = self.network(*[_t(i) for i in inputs])
            loss = self._loss(out, *[_t(l) for l in labels])
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        import paddle_tpu as paddle
        from ..core import autograd
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = (labels if isinstance(labels, (list, tuple))
                  else ([labels] if labels is not None else []))
        with autograd.no_grad():
            out = self.network(*[_t(i) for i in inputs])
            loss = (self._loss(out, *[_t(l) for l in labels])
                    if self._loss and labels else None)
        metrics = []
        for m in self._metrics:
            res = m.compute(out, *[_t(l) for l in labels])
            m.update(res)
            metrics.append(m.accumulate())
        return ([float(loss)] if loss is not None else []), metrics, out

    def predict_batch(self, inputs):
        from ..core import autograd
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        was = self.network.training
        self.network.eval()
        with autograd.no_grad():
            out = self.network(*[_t(i) for i in inputs])
        if was:
            self.network.train()
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        """reference: hapi/model.py:1299."""
        loader = self._to_loader(train_data, batch_size, shuffle,
                                 num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False,
                                      num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, verbose=verbose,
                                metrics=[m.name() for m in self._metrics])
        if save_dir:
            from .callbacks import ModelCheckpoint
            cbks.callbacks.append(ModelCheckpoint(save_freq, save_dir))
            cbks.callbacks[-1].set_model(self)
        self.stop_training = False
        cbks.on_train_begin()
        history = {"loss": []}
        try:
            for epoch in range(epochs):
                self.network.train()
                cbks.on_epoch_begin(epoch)
                logs = {}
                for step, batch in enumerate(loader):
                    cbks.on_train_batch_begin(step)
                    ins, labs = self._split_batch(batch)
                    loss = self.train_batch(list(ins), list(labs))
                    logs = {"loss": loss[0]}
                    if step % max(log_freq, 1) == 0:
                        cbks.on_train_batch_end(step, logs)
                history["loss"].append(logs.get("loss"))
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              _callbacks=cbks)
                    cbks.on_eval_end(eval_logs)
                if self.stop_training:
                    break
        finally:
            # even when training raises: callbacks holding process-wide
            # resources (Checkpoint's SIGTERM handler) must release them
            cbks.on_train_cleanup()
        cbks.on_train_end(logs)
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        loader = self._to_loader(eval_data, batch_size, False, num_workers)
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            ins, labs = self._split_batch(batch)
            loss, metrics, _ = self.eval_batch(list(ins), list(labs))
            if loss:
                losses.append(loss[0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            if isinstance(name, list):
                vals = m.accumulate()
                logs.update(dict(zip(name, vals)))
            else:
                logs[name] = m.accumulate()
        self.network.train()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = (self._split_batch(batch)
                      if isinstance(batch, (list, tuple)) else ((batch,), ()))
            out = self.predict_batch(list(ins))
            outputs.append(out)
        if stack_outputs and outputs:
            first = outputs[0]
            if isinstance(first, Tensor):
                return [np.concatenate([np.asarray(o.data)
                                        for o in outputs])]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        import paddle_tpu as paddle
        if training:
            paddle.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                paddle.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit
            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle
        self.network.set_state_dict(paddle.load(path + ".pdparams"))
        import os
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(path + ".pdopt")):
            self._optimizer.set_state_dict(paddle.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from . import summary as _summary
        return _summary(self.network, input_size)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _wrap_loss(loss):
    """Multi-loss support: a loss returning a list/tuple is summed
    (reference: hapi/model.py _run_one_epoch sums loss lists)."""
    def fn(out, *labels):
        val = loss(out, *labels)
        if isinstance(val, (list, tuple)):
            total = val[0]
            for v in val[1:]:
                total = total + v
            return total
        return val
    fn.__name__ = getattr(loss, "__name__", "loss")
    return fn
