"""Device management.

TPU-native equivalent of the reference's Place / DeviceContextPool layer
(reference: paddle/fluid/platform/place.h:103, device_context.h:695).  On
TPU, XLA owns streams and contexts; what remains is device *selection* and
queries over ``jax.devices()``.
"""
from __future__ import annotations

import jax

_current_device = None


def get_all_devices():
    return jax.devices()


def device_count(kind=None) -> int:
    return len(jax.devices(kind) if kind else jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def is_compiled_with_xpu() -> bool:
    return False


def set_device(device: str):
    """paddle.set_device parity: 'cpu' | 'tpu' | 'tpu:0' | 'gpu' (→ tpu)."""
    global _current_device
    kind = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if kind in ("gpu", "cuda", "tpu", "xpu"):
        kind = "tpu" if is_compiled_with_tpu() else None
    if kind in (None, "tpu") and is_compiled_with_tpu():
        _current_device = jax.devices("tpu")[idx]
    else:
        _current_device = jax.devices("cpu")[min(idx, device_count("cpu") - 1)]
    jax.config.update("jax_default_device", _current_device)
    return _current_device


def get_device() -> str:
    d = _current_device or jax.devices()[0]
    return f"{d.platform}:{d.id}"
