"""Quantized, bucketed gradient collectives with error feedback.

The gradient-communication stage the sharded static Executor (and
``SpmdTrainStep``) lowers in-graph between backward and the optimizer
update — ROADMAP item 2, after EQuARX (block-scaled quantized AllReduce
inside XLA) and T3 (compute-collective overlap via bucketing):

- **Quantized reduction** — gradients cross the wire as block-scaled
  int8 (one f32 absmax scale per ``block_size`` elements) or bf16
  instead of fp32.  The int8 route is the two-shot bandwidth algorithm:
  each device quantizes its local (residual-corrected) gradient,
  ``all_to_all`` exchanges int8 chunks + scales, every device
  dequantizes and sums its chunk in f32, requantizes, and an
  ``all_gather`` of int8 chunks + scales rebuilds the reduced tensor —
  both directions carry quantized payload, so wire bytes are ~1/4 of a
  fp32 ring allreduce (+ scale overhead).
- **Error feedback** — the quantization error each device incurs
  (local quantize error, plus the requantize error on the chunk it
  owns) is returned as a per-device residual and added back into the
  next step's gradient before quantization, so the *sum* of applied
  updates tracks the sum of true gradients and the loss trajectory
  stays at parity with fp32 collectives.  The residual is
  device-varying state; the static Executor carries it in the donated
  ``_ExecState`` aux tree (sharded ``[dp, numel]``).
- **Bucketing** — small gradients fuse into flat buckets of
  ``strategy.fuse_grad_size_in_MB``, assembled in *backward production
  order* (the reverse of parameter creation order: the last layer's
  grads exist first).  Each bucket is reduced by its own independent
  collective, so XLA's latency-hiding scheduler can overlap the
  reduction of bucket N with the backward computation producing bucket
  N-1's gradients — one monolithic post-backward reduction would be a
  barrier (the reference Reducer's design, reducer.cc, in-graph).
- **Algorithm selection by message size** — buckets whose quantized
  payload is at least ``scatter_threshold_KB`` take the
  bandwidth-optimal scatter route (``psum_scatter``+``all_gather``, or
  the int8 two-shot above); smaller latency-bound buckets take one
  fused ``psum`` (at bf16 wire when the config asks for int8 — a
  single-shot int8 psum cannot sum payloads carrying per-device
  scales).  Every choice is recorded on the plan and surfaced through
  ``comm.*`` monitor stats and the static cost model.

Everything here is shape-static: :func:`plan_reduction` computes the
buckets, algorithms and exact per-device wire bytes from gradient
shapes alone, so the cost model's prediction and the runtime's
``comm.wire_bytes`` stat are the *same number* by construction.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import DP_AXIS

__all__ = [
    "CommSpec", "Bucket", "GradCommPlan", "resolve", "plan_reduction",
    "build_buckets", "flatten_bucket", "unflatten_bucket",
    "quantize_int8_blocks", "dequantize_int8_blocks", "reduce_gradients",
    "source_label", "incompatibility", "plan_status",
]

_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "int8": 1}
_SCALE_BYTES = 4  # one f32 absmax per block


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CommSpec:
    """Resolved, hashable grad-comm configuration (strategy knobs +
    bucket size + which toggle asked for it)."""
    dtype: str                    # 'fp32' | 'bf16' | 'int8'
    block_size: int
    error_feedback: bool
    scatter_threshold_KB: float
    fuse_grad_size_in_MB: float
    source: str                   # 'grad_comm' | 'fp16_allreduce'

    def fingerprint(self) -> tuple:
        return (self.dtype, self.block_size, self.error_feedback,
                float(self.scatter_threshold_KB),
                float(self.fuse_grad_size_in_MB))


def resolve(strategy) -> Optional[CommSpec]:
    """The effective grad-comm spec of a DistributedStrategy, or None
    when gradient reduction stays with GSPMD's default lowering.

    ``strategy.grad_comm.dtype`` wins; ``strategy.fp16_allreduce`` is
    the backward-compatible alias for a bf16 wire (without error
    feedback — the historical semantics of the bf16 psum graft)."""
    if strategy is None:
        return None
    gc = getattr(strategy, "grad_comm", None)
    fuse = float(getattr(strategy, "fuse_grad_size_in_MB", 32) or 32)
    if gc is not None and gc.dtype is not None:
        return CommSpec(str(gc.dtype), int(gc.block_size),
                        bool(gc.error_feedback),
                        float(gc.scatter_threshold_KB), fuse, "grad_comm")
    if getattr(strategy, "fp16_allreduce", False):
        block = int(gc.block_size) if gc is not None else 256
        thresh = (float(gc.scatter_threshold_KB) if gc is not None
                  else 32.0)
        return CommSpec("bf16", block, False, thresh, fuse,
                        "fp16_allreduce")
    return None


# ---------------------------------------------------------------------------
# activation / compatibility (ONE predicate for every consumer)
# ---------------------------------------------------------------------------

def source_label(cfg: CommSpec) -> str:
    """The user-facing name of whichever toggle asked for the stage."""
    return ("strategy.fp16_allreduce" if cfg.source == "fp16_allreduce"
            else f'strategy.grad_comm (dtype="{cfg.dtype}")')


def incompatibility(cfg: CommSpec, mesh_shape,
                    sharded_params: Sequence[str] = ()) -> Optional[str]:
    """Why the explicit shard_map reduction cannot run on this mesh /
    param layout, or None when it can.  The single source of the
    constraint messages — SpmdTrainStep, the Executor and the cost
    model all consult this, so they cannot drift apart."""
    src = source_label(cfg)
    others = [a for a, s in dict(mesh_shape).items()
              if a != DP_AXIS and s > 1]
    if others:
        return (f"{src} covers the data-parallel grad reduction; mesh "
                f"axes {others} carry model shardings whose collectives "
                f"GSPMD schedules — run it on a pure-dp mesh.")
    sharded = list(sharded_params)
    if sharded:
        return (f"{src} + dp-sharded params (ZeRO-3 / partition rules: "
                f"{sharded[:4]}): the explicit shard_map grad path "
                f"would replicate them.  Keep params replicated (ZeRO "
                f"stage <= 2) with it.")
    return None


def plan_status(plan) -> Tuple[str, Optional[str]]:
    """Activation state of a ShardingPlan's grad_comm spec:
    ``('off', None)`` — no spec, or a 1-device dp axis (nothing crosses
    a wire); ``('active', None)`` — the Executor lowers the stage;
    ``('error', msg)`` — configured but impossible (the Executor raises
    ``msg``; the cost model reports it).  Executor and cost model share
    this predicate so measured and predicted can never disagree about
    WHICH path runs."""
    cfg = getattr(plan, "grad_comm", None)
    if cfg is None:
        return "off", None
    if dict(plan.mesh.shape).get(DP_AXIS, 1) <= 1:
        return "off", None
    from .sharding import spec_axes
    sharded = [n for n, s in zip(plan.param_names, plan.param_specs)
               if spec_axes(s)]
    msg = incompatibility(cfg, plan.mesh.shape, sharded)
    if msg is not None:
        return "error", msg
    return "active", None


# ---------------------------------------------------------------------------
# block-scaled int8 quantization
# ---------------------------------------------------------------------------

def quantize_int8_blocks(x, block_size: int):
    """1-D float array -> (int8 blocks ``[nb, B]``, f32 scales
    ``[nb, 1]``).  Pads to a block multiple; scale = absmax/127 per
    block (zero blocks get scale 1 so dequantize is exact zero)."""
    n = x.shape[0]
    pad = (-n) % block_size
    xp = jnp.pad(x.astype(jnp.float32), (0, pad)).reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(xp), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xp / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8_blocks(q, scales, numel: int):
    """Inverse of :func:`quantize_int8_blocks` (drops the padding)."""
    return (q.astype(jnp.float32) * scales).reshape(-1)[:numel]


# ---------------------------------------------------------------------------
# buckets + plan
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Bucket:
    """One fused reduction: which grads it carries (in backward
    production order), how it crosses the wire, and what that costs."""
    indices: Tuple[int, ...]      # positions into the grad list
    shapes: Tuple[tuple, ...]
    sizes: Tuple[int, ...]        # numels, aligned with indices
    numel: int
    algorithm: str                # 'psum' | 'scatter' | 'none'
    wire_dtype: str               # 'fp32' | 'bf16' | 'int8'
    wire_bytes: int               # per-device bytes per step
    collectives: int
    carries_residual: bool

    @property
    def classification(self) -> str:
        return ("none" if self.algorithm == "none"
                else "bandwidth" if self.algorithm == "scatter"
                else "latency")

    def to_dict(self) -> dict:
        return {
            "params": list(self.indices), "numel": self.numel,
            "algorithm": self.algorithm, "wire_dtype": self.wire_dtype,
            "wire_bytes": self.wire_bytes,
            "collectives": self.collectives,
            "classification": self.classification,
            "error_feedback": self.carries_residual,
        }


def build_buckets(shapes: Sequence[tuple], fuse_mb: float
                  ) -> List[Tuple[Tuple[int, ...], int]]:
    """Greedy bucket assembly over grads in backward production order
    (reverse of the given creation order).  Returns ``[(indices,
    numel)]``; every index appears exactly once, each bucket holds at
    most ``fuse_mb`` MB of f32 payload (a single grad larger than the
    budget gets its own bucket)."""
    budget = max(int(float(fuse_mb) * (1 << 20)) // 4, 1)  # f32 elements
    out: List[Tuple[Tuple[int, ...], int]] = []
    cur: List[int] = []
    cur_n = 0
    for i in reversed(range(len(shapes))):
        n = 1
        for d in shapes[i]:
            n *= int(d)
        if cur and cur_n + n > budget:
            out.append((tuple(cur), cur_n))
            cur, cur_n = [], 0
        cur.append(i)
        cur_n += n
    if cur:
        out.append((tuple(cur), cur_n))
    return out


def _padded_numel(numel: int, multiple: int) -> int:
    return int(math.ceil(numel / multiple)) * multiple if multiple > 1 \
        else numel


def _int8_payload(numel: int, dp: int, block_size: int) -> int:
    """One direction's int8 wire payload: values padded so each device
    owns a block-aligned chunk, plus one f32 scale per block.  The ONE
    formula both the scatter-vs-psum threshold and the wire-byte
    accounting use — they must agree or the recorded bytes would not
    match the algorithm actually chosen."""
    np_ = _padded_numel(numel, dp * block_size)
    return np_ * 1 + (np_ // block_size) * _SCALE_BYTES


def _wire_bytes(numel: int, wire_dtype: str, algorithm: str, dp: int,
                block_size: int) -> int:
    """Exact per-device wire bytes of one bucket's reduction under the
    ring model: an allreduce (or its reduce-scatter + all-gather
    decomposition) moves ``2*(dp-1)/dp`` of the payload through every
    device's links per step."""
    if dp <= 1 or algorithm == "none":
        return 0
    ring = 2.0 * (dp - 1) / dp
    if wire_dtype == "int8":
        # scatter route: quantized payload + scales ride both directions
        payload = _int8_payload(numel, dp, block_size)
    elif algorithm == "scatter":
        payload = _padded_numel(numel, dp) * _WIRE_ITEMSIZE[wire_dtype]
    else:
        payload = numel * _WIRE_ITEMSIZE[wire_dtype]
    return int(round(ring * payload))


class GradCommPlan:
    """Static reduction plan: buckets, algorithms, wire bytes.

    Built once per compile from gradient shapes; the in-graph
    :func:`reduce_gradients` follows it exactly, and its byte totals
    are what the Executor reports as ``comm.wire_bytes`` per step and
    the cost model reports as ``predicted_wire_bytes``."""

    __slots__ = ("cfg", "dp", "buckets", "wire_bytes_per_step",
                 "collectives_per_step", "fp32_wire_bytes_per_step")

    def __init__(self, cfg: CommSpec, dp: int, buckets: List[Bucket]):
        self.cfg = cfg
        self.dp = int(dp)
        self.buckets = buckets
        self.wire_bytes_per_step = sum(b.wire_bytes for b in buckets)
        self.collectives_per_step = sum(b.collectives for b in buckets)
        # the un-quantized, un-bucketed baseline the ratio gates measure
        # against: one fp32 ring allreduce over every gradient byte
        total = sum(b.numel for b in buckets)
        self.fp32_wire_bytes_per_step = _wire_bytes(
            total, "fp32", "psum", self.dp, cfg.block_size)

    @property
    def residual_buckets(self) -> List[Bucket]:
        return [b for b in self.buckets if b.carries_residual]

    def algo_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for b in self.buckets:
            out[b.algorithm] = out.get(b.algorithm, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "dtype": self.cfg.dtype, "dp": self.dp,
            "block_size": self.cfg.block_size,
            "error_feedback": self.cfg.error_feedback,
            "scatter_threshold_KB": self.cfg.scatter_threshold_KB,
            "fuse_grad_size_in_MB": self.cfg.fuse_grad_size_in_MB,
            "wire_bytes_per_step": self.wire_bytes_per_step,
            "fp32_wire_bytes_per_step": self.fp32_wire_bytes_per_step,
            "collectives_per_step": self.collectives_per_step,
            "buckets": [b.to_dict() for b in self.buckets],
        }

    def __repr__(self):
        return (f"GradCommPlan(dtype={self.cfg.dtype}, dp={self.dp}, "
                f"buckets={len(self.buckets)}, "
                f"wire={self.wire_bytes_per_step}B/step "
                f"[fp32 {self.fp32_wire_bytes_per_step}B], "
                f"algos={self.algo_counts()})")


def plan_reduction(shapes: Sequence[tuple], dp: int, cfg: CommSpec
                   ) -> GradCommPlan:
    """Assemble buckets over gradient ``shapes`` (creation order) and
    pick each bucket's wire dtype + collective algorithm."""
    buckets: List[Bucket] = []
    for indices, numel in build_buckets(shapes, cfg.fuse_grad_size_in_MB):
        if dp <= 1:
            algo, wire = "none", cfg.dtype
        else:
            # threshold compares the QUANTIZED payload (what the
            # scatter route would put on the wire, one direction)
            if cfg.dtype == "int8":
                payload = _int8_payload(numel, dp, cfg.block_size)
            else:
                payload = numel * _WIRE_ITEMSIZE[cfg.dtype]
            if payload >= cfg.scatter_threshold_KB * 1024:
                algo, wire = "scatter", cfg.dtype
            else:
                # latency-bound: one fused psum.  A single-shot int8
                # psum cannot sum payloads carrying per-device scales,
                # so the int8 config's small buckets ride bf16 wire.
                algo = "psum"
                wire = "bf16" if cfg.dtype == "int8" else cfg.dtype
        if algo == "none":
            n_coll = 0
        elif algo == "psum":
            n_coll = 1
        elif wire == "int8":
            n_coll = 4      # all_to_all q, all_to_all scales, ag q, ag s
        else:
            n_coll = 2      # psum_scatter + all_gather
        carries = (cfg.error_feedback and algo != "none"
                   and wire != "fp32")
        buckets.append(Bucket(
            indices=indices,
            shapes=tuple(tuple(shapes[i]) for i in indices),
            sizes=tuple(int(np.prod(shapes[i])) if shapes[i] else 1
                        for i in indices),
            numel=numel, algorithm=algo, wire_dtype=wire,
            wire_bytes=_wire_bytes(numel, wire, algo, dp, cfg.block_size),
            collectives=n_coll, carries_residual=carries))
    return GradCommPlan(cfg, dp, buckets)


# ---------------------------------------------------------------------------
# bucket (dis)assembly — bitwise
# ---------------------------------------------------------------------------

def flatten_bucket(grads: Sequence, bucket: Bucket):
    """Concatenate the bucket's grads into one flat f32 vector (in the
    bucket's production order)."""
    return jnp.concatenate(
        [jnp.asarray(grads[i], jnp.float32).reshape(-1)
         for i in bucket.indices])


def unflatten_bucket(flat, bucket: Bucket, like: Sequence):
    """Split a flat vector back into the bucket's grads — bitwise
    inverse of :func:`flatten_bucket` (shape AND dtype restored from
    ``like``).  Returns ``[(index, grad)]``."""
    out = []
    off = 0
    for i, n, shp in zip(bucket.indices, bucket.sizes, bucket.shapes):
        piece = jax.lax.slice_in_dim(flat, off, off + n).reshape(shp)
        out.append((i, piece.astype(like[i].dtype)))
        off += n
    return out


# ---------------------------------------------------------------------------
# in-graph reduction (call INSIDE shard_map over the dp axis)
# ---------------------------------------------------------------------------

def _rs_ag(x, axis_name: str, dp: int):
    """Bandwidth route for non-int8 wire: pad to a dp multiple,
    psum_scatter (each device reduces its chunk), all_gather back."""
    n = x.shape[0]
    np_ = _padded_numel(n, dp)
    xp = jnp.pad(x, (0, np_ - n))
    chunk = jax.lax.psum_scatter(xp, axis_name, scatter_dimension=0,
                                 tiled=True)
    return jax.lax.all_gather(chunk, axis_name, tiled=True)[:n]


def _reduce_int8_scatter(carry, axis_name: str, dp: int, block: int,
                         error_feedback: bool):
    """The two-shot block-scaled int8 reduction.  ``carry`` is the
    residual-corrected local gradient (flat f32).  Returns (reduced sum
    as f32, per-device residual or None)."""
    n = carry.shape[0]
    np_ = _padded_numel(n, dp * block)
    chunk = np_ // dp
    cb = chunk // block
    # shot 1: quantize local, exchange chunks (int8 + scales on wire)
    q, s = quantize_int8_blocks(jnp.pad(carry, (0, np_ - n)), block)
    qq = jax.lax.all_to_all(q.reshape(dp, cb, block), axis_name, 0, 0)
    ss = jax.lax.all_to_all(s.reshape(dp, cb, 1), axis_name, 0, 0)
    # dequantize per peer, sum in f32: my chunk of the global sum
    red_chunk = jnp.sum(qq.astype(jnp.float32) * ss, axis=0).reshape(-1)
    # shot 2: requantize the reduced chunk, gather (int8 + scales)
    q2, s2 = quantize_int8_blocks(red_chunk, block)
    qg = jax.lax.all_gather(q2.reshape(-1), axis_name, tiled=True)
    sg = jax.lax.all_gather(s2.reshape(-1), axis_name, tiled=True)
    total = dequantize_int8_blocks(qg.reshape(-1, block),
                                   sg.reshape(-1, 1), n)
    if not error_feedback:
        return total, None
    # residual: my local quantize error everywhere, PLUS the requantize
    # error on the chunk I own (I am the only device that knows it; the
    # next step's psum recovers it exactly once)
    e1 = jnp.pad(carry, (0, np_ - n)) - dequantize_int8_blocks(q, s, np_)
    e2 = red_chunk - dequantize_int8_blocks(q2, s2, chunk)
    idx = jax.lax.axis_index(axis_name)
    own = jax.lax.dynamic_slice(e1, (idx * chunk,), (chunk,))
    e1 = jax.lax.dynamic_update_slice(e1, own + e2, (idx * chunk,))
    return total, e1[:n]


def _reduce_bucket(flat, residual, axis_name: str, bucket: Bucket,
                   plan: GradCommPlan):
    """Reduce one flat bucket over the dp axis following the plan.
    Returns (mean-reduced f32 vector, new residual or None)."""
    dp = plan.dp
    if bucket.algorithm == "none":
        return flat, residual
    carry = flat + residual if residual is not None else flat
    wire = bucket.wire_dtype
    if wire == "fp32":
        total = (jax.lax.psum(carry, axis_name)
                 if bucket.algorithm == "psum"
                 else _rs_ag(carry, axis_name, dp))
        new_res = residual
        if residual is not None:  # fp32 wire is exact: residual drains
            new_res = jnp.zeros_like(residual)
        return total / dp, new_res
    if wire == "bf16":
        sent = carry.astype(jnp.bfloat16)
        total = (jax.lax.psum(sent, axis_name)
                 if bucket.algorithm == "psum"
                 else _rs_ag(sent, axis_name, dp)).astype(jnp.float32)
        new_res = (carry - sent.astype(jnp.float32)
                   if bucket.carries_residual and residual is not None
                   else None)
        return total / dp, new_res
    total, new_res = _reduce_int8_scatter(
        carry, axis_name, dp, plan.cfg.block_size,
        bucket.carries_residual and residual is not None)
    return total / dp, new_res


def reduce_gradients(grads: Sequence, *, plan: GradCommPlan,
                     axis_name: str = DP_AXIS,
                     residuals: Optional[Sequence] = None):
    """Reduce per-shard gradients to their dp-mean following ``plan``.

    Must be called INSIDE a ``shard_map`` over ``axis_name``: ``grads``
    are the local (device-varying) gradients, one entry per trainable
    param in creation order.  ``residuals`` is the per-device error-
    feedback carry — one flat f32 vector per ``plan.residual_buckets``
    entry, in plan order — or None to reduce without error feedback
    (the residual-less SpmdTrainStep path).

    Returns ``(reduced grads, new residuals)``; reduced grads come back
    replicated (every device holds the same mean), in the original
    order/shape/dtype.  Buckets are emitted in backward production
    order, each as an independent collective, so the XLA scheduler can
    overlap bucket N's reduction with bucket N-1's producers."""
    out = list(grads)
    new_res: List = []
    ri = 0
    for bucket in plan.buckets:
        res = None
        if residuals is not None and bucket.carries_residual:
            res = residuals[ri]
        flat = flatten_bucket(grads, bucket)
        red, r2 = _reduce_bucket(flat, res, axis_name, bucket, plan)
        if residuals is not None and bucket.carries_residual:
            new_res.append(r2 if r2 is not None
                           else jnp.zeros_like(flat))
            ri += 1
        for i, g in unflatten_bucket(red, bucket, grads):
            out[i] = g
    return out, new_res
