"""Quantized, bucketed gradient collectives with error feedback.

The gradient-communication stage the sharded static Executor (and
``SpmdTrainStep``) lowers in-graph between backward and the optimizer
update — ROADMAP item 2, after EQuARX (block-scaled quantized AllReduce
inside XLA) and T3 (compute-collective overlap via bucketing):

- **Quantized reduction** — gradients cross the wire as block-scaled
  int8 (one f32 absmax scale per ``block_size`` elements) or bf16
  instead of fp32.  The int8 route is the two-shot bandwidth algorithm:
  each device quantizes its local (residual-corrected) gradient,
  ``all_to_all`` exchanges int8 chunks + scales, every device
  dequantizes and sums its chunk in f32, requantizes, and an
  ``all_gather`` of int8 chunks + scales rebuilds the reduced tensor —
  both directions carry quantized payload, so wire bytes are ~1/4 of a
  fp32 ring allreduce (+ scale overhead).
- **Error feedback** — the quantization error each device incurs
  (local quantize error, plus the requantize error on the chunk it
  owns) is returned as a per-device residual and added back into the
  next step's gradient before quantization, so the *sum* of applied
  updates tracks the sum of true gradients and the loss trajectory
  stays at parity with fp32 collectives.  The residual is
  device-varying state; the static Executor carries it in the donated
  ``_ExecState`` aux tree (sharded ``[dp, numel]``).
- **Bucketing** — small gradients fuse into flat buckets of
  ``strategy.fuse_grad_size_in_MB``, assembled in *backward production
  order*: the order reverse-mode AD finalizes each gradient, derived
  from the DefUseGraph's backward levels (:func:`production_order` —
  a parameter's grad is complete once the VJPs of ALL its consumers
  have run, so a shallow skip-branch param's grad exists earlier than
  a deep trunk param's even when the trunk was recorded later).  Each
  bucket is reduced by its own independent collective, so bucket N's
  reduction can overlap the backward computation still producing
  bucket N+1's gradients — one monolithic post-backward reduction
  would be a barrier (the reference Reducer's design, reducer.cc,
  in-graph).
- **Compute-collective overlap** (``strategy.grad_comm.overlap``,
  T3-style) — how aggressively the collectives hide behind backward:
  ``"none"`` pins the whole comm stage after backward (an
  ``optimization_barrier`` makes every bucket depend on every grad —
  the measured no-overlap baseline, step time = compute + comm);
  ``"auto"`` picks per backend (:func:`resolve_overlap_path`): on
  TPU/GPU with ``FLAGS_xla_latency_hiding`` on (set BEFORE backend
  init — ``core/xla_env.py``) the per-bucket collectives are left
  early in the HLO for the latency-hiding scheduler to split into
  async start/done pairs; on TPU/GPU without it the explicit
  ``"ring"`` fallback runs (the compiler won't schedule collectives
  asynchronously, so hand it pre-chunked ones); on CPU the fused form
  (nothing overlaps on a serial backend — chunking is pure rendezvous
  overhead there); ``"ring"`` lowers each bandwidth-route bucket as a
  ppermute-chunked ring reduce-scatter/all-gather — every ring step
  is a small independent single-chunk collective the scheduler can
  slot between backward ops even without latency-hiding support.
  The ring accumulates each chunk in ascending absolute device order,
  which makes its fp32 result *bitwise identical* to the
  ``psum_scatter``+``all_gather`` route (property-tested), so a path
  flip can never change training numerics at fp32 wire.
- **Algorithm selection by message size** — buckets whose quantized
  payload is at least ``scatter_threshold_KB`` take the
  bandwidth-optimal scatter route (``psum_scatter``+``all_gather``, or
  the int8 two-shot above); smaller latency-bound buckets take one
  fused ``psum`` (at bf16 wire when the config asks for int8 — a
  single-shot int8 psum cannot sum payloads carrying per-device
  scales).  Every choice is recorded on the plan and surfaced through
  ``comm.*`` monitor stats and the static cost model.

Everything here is shape-static: :func:`plan_reduction` computes the
buckets, algorithms and exact per-device wire bytes from gradient
shapes alone, so the cost model's prediction and the runtime's
``comm.wire_bytes`` stat are the *same number* by construction.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import DP_AXIS, MP_AXIS

__all__ = [
    "CommSpec", "Bucket", "GradCommPlan", "resolve", "plan_reduction",
    "build_buckets", "flatten_bucket", "unflatten_bucket",
    "quantize_int8_blocks", "dequantize_int8_blocks", "reduce_gradients",
    "source_label", "incompatibility", "plan_status", "classify_spec",
    "hybrid_layout", "plan_gathers", "gather_param", "bucket_flat_numel",
    "resolve_overlap_path", "production_order",
]

OVERLAP_MODES = ("none", "auto", "ring")

_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2, "int8": 1}
_SCALE_BYTES = 4  # one f32 absmax per block


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CommSpec:
    """Resolved, hashable grad-comm configuration (strategy knobs +
    bucket size + which toggle asked for it)."""
    dtype: str                    # 'fp32' | 'bf16' | 'int8'
    block_size: int
    error_feedback: bool
    scatter_threshold_KB: float
    fuse_grad_size_in_MB: float
    source: str                   # 'grad_comm' | 'fp16_allreduce'
    overlap: str = "auto"         # 'none' | 'auto' | 'ring'

    def fingerprint(self) -> tuple:
        return (self.dtype, self.block_size, self.error_feedback,
                float(self.scatter_threshold_KB),
                float(self.fuse_grad_size_in_MB), self.overlap)


def resolve(strategy) -> Optional[CommSpec]:
    """The effective grad-comm spec of a DistributedStrategy, or None
    when gradient reduction stays with GSPMD's default lowering.

    ``strategy.grad_comm.dtype`` wins; ``strategy.fp16_allreduce`` is
    the backward-compatible alias for a bf16 wire (without error
    feedback — the historical semantics of the bf16 psum graft)."""
    if strategy is None:
        return None
    gc = getattr(strategy, "grad_comm", None)
    fuse = float(getattr(strategy, "fuse_grad_size_in_MB", 32) or 32)
    overlap = str(getattr(gc, "overlap", "auto") or "auto") \
        if gc is not None else "auto"
    if gc is not None and gc.dtype is not None:
        return CommSpec(str(gc.dtype), int(gc.block_size),
                        bool(gc.error_feedback),
                        float(gc.scatter_threshold_KB), fuse, "grad_comm",
                        overlap)
    if getattr(strategy, "fp16_allreduce", False):
        block = int(gc.block_size) if gc is not None else 256
        thresh = (float(gc.scatter_threshold_KB) if gc is not None
                  else 32.0)
        return CommSpec("bf16", block, False, thresh, fuse,
                        "fp16_allreduce", overlap)
    return None


def resolve_overlap_path(cfg: "CommSpec", backend: Optional[str] = None
                         ) -> str:
    """The lowering path the ``overlap`` knob resolves to on this
    backend: ``'none'`` (barriered, comm strictly after backward),
    ``'xla'`` (per-bucket fused collectives left early in the HLO,
    dependent only on their own grads), or ``'ring'`` (explicit
    ppermute-chunked ring reduce-scatter/all-gather).

    ``'auto'`` policy: on TPU/GPU with the latency-hiding scheduler on
    (``FLAGS_xla_latency_hiding``) the fused form wins — the scheduler
    splits each collective into an async start/done pair and hoists
    the start across backward.  On TPU/GPU *without* it the compiler
    won't schedule collectives asynchronously, so the explicit ring is
    the fallback: dp-1 single-chunk steps per direction give even a
    static scheduler small independent units to slot between backward
    ops.  On CPU (and anything else) the fused form again: XLA:CPU
    executes one thunk at a time, so there is nothing to overlap and
    chunking only adds per-step rendezvous overhead (measured ~1.2x
    step time on the 8-virtual-device smoke)."""
    if cfg.overlap == "none":
        return "none"
    if cfg.overlap == "ring":
        return "ring"
    if backend is None:
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - backend not initialisable
            backend = "cpu"
    if backend in ("tpu", "gpu"):
        # consult what actually reached XLA_FLAGS, not the raw knob: a
        # FLAGS_xla_latency_hiding set too late (post backend init) or
        # on a platform the detector missed never enabled the
        # scheduler, and compiling the fused path would leave every
        # collective synchronous while the cost model calls it hidden
        from ..core.xla_env import latency_hiding_active
        return "xla" if latency_hiding_active(backend) else "ring"
    return "xla"


# ---------------------------------------------------------------------------
# activation / compatibility (ONE predicate for every consumer)
# ---------------------------------------------------------------------------

def source_label(cfg: CommSpec) -> str:
    """The user-facing name of whichever toggle asked for the stage."""
    return ("strategy.fp16_allreduce" if cfg.source == "fp16_allreduce"
            else f'strategy.grad_comm (dtype="{cfg.dtype}")')


def format_mesh_axes(mesh_shape, exclude: Sequence[str] = ()) -> str:
    """``'mp=2, pp=4'`` — the ONE axis=degree renderer every mesh-shape
    constraint message goes through (:func:`incompatibility` here,
    ``strategy.infer_mesh_shape``'s divisibility error, shardcheck
    diagnostics), so the texts name the offending axis and degree
    everywhere and cannot drift apart."""
    return ", ".join(f"{a}={int(s)}" for a, s in dict(mesh_shape).items()
                     if a not in exclude and int(s) > 1)


def classify_spec(spec, mesh_shape) -> Tuple[str, Optional[int]]:
    """Which hybrid grad-comm form a param's PartitionSpec takes on
    this mesh: ``('rep', None)`` replicated, ``('fsdp', 0)`` dp-sharded
    on dim 0 (ZeRO-3 — gathered over dp ahead of forward, grads
    reduce-scattered back to shards), ``('mp', dim)`` mp-sharded on one
    tensor dim (gathered over mp ahead of forward, grads sliced back),
    or ``('bad', why)`` for layouts the shard_map stage cannot carry
    (multi-dim / multi-axis shards, dp off dim 0, pp/sp shards)."""
    shape = dict(mesh_shape)
    hits = []  # (tensor dim, mesh axes active on it)
    for d, e in enumerate(tuple(spec) if spec is not None else ()):
        if e is None:
            continue
        axes = [a for a in ((e,) if isinstance(e, str) else tuple(e))
                if int(shape.get(a, 1)) > 1]
        if axes:
            hits.append((d, tuple(axes)))
    if not hits:
        return "rep", None
    if len(hits) > 1:
        return "bad", "sharded over more than one tensor dimension"
    d, axes = hits[0]
    if len(axes) > 1:
        return "bad", (f"dim {d} sharded over multiple mesh axes "
                       f"{list(axes)}")
    ax = axes[0]
    if ax == DP_AXIS:
        if d != 0:
            return "bad", (f"dp-sharded on dim {d} — the FSDP form "
                           f"shards dim 0 only")
        return "fsdp", 0
    if ax == MP_AXIS:
        return "mp", d
    return "bad", (f"sharded over mesh axis {ax!r} — only 'dp' (dim 0) "
                   f"and 'mp' shards compose with grad_comm")


def incompatibility(cfg: CommSpec, mesh_shape,
                    sharded_params: Sequence = (),
                    hybrid: bool = False) -> Optional[str]:
    """Why the explicit shard_map reduction cannot run on this mesh /
    param layout, or None when it can.  The single source of the
    constraint messages — SpmdTrainStep, the Executor, the cost model
    and the static shardcheck passes all consult this, so they cannot
    drift apart.

    Two lowerings share this predicate.  ``hybrid=False`` is the
    restricted SpmdTrainStep form (params closed over replicated; any
    non-dp mesh axis or sharded param is rejected; ``sharded_params``
    is a sequence of names).  ``hybrid=True`` is the static Executor's
    composed form: 'mp' mesh axes and FSDP/'mp' param shards are
    first-class (params enter the shard_map per their spec and are
    all-gathered ahead of forward; FSDP grads reduce-scatter back to
    shards), so only pp/sp axes and spec shapes outside the two
    supported forms (see :func:`classify_spec`) reject —
    ``sharded_params`` is then ``(name, spec)`` pairs."""
    src = source_label(cfg)
    if not hybrid:
        others = format_mesh_axes(mesh_shape, exclude=(DP_AXIS,))
        if others:
            return (f"{src} covers the data-parallel grad reduction; "
                    f"mesh axes [{others}] carry model shardings whose "
                    f"collectives GSPMD schedules — run it on a "
                    f"pure-dp mesh, or use the static Executor, whose "
                    f"grad_comm stage composes dp with 'mp' and FSDP "
                    f"shards.")
        sharded = list(sharded_params)
        if sharded:
            return (f"{src} + dp-sharded params (ZeRO-3 / partition "
                    f"rules: {sharded[:4]}): the explicit shard_map "
                    f"grad path would replicate them.  Keep params "
                    f"replicated (ZeRO stage <= 2) with it, or use the "
                    f"static Executor, which gathers FSDP shards ahead "
                    f"of forward and reduce-scatters grads back.")
        return None
    others = format_mesh_axes(mesh_shape, exclude=(DP_AXIS, MP_AXIS))
    if others:
        return (f"{src} composes the data-parallel grad reduction "
                f"with tensor-parallel 'mp' param gathers; mesh axes "
                f"[{others}] schedule cross-stage collectives "
                f"(pipeline/sequence parallel) this shard_map stage "
                f"cannot carry — drop those axes from the mesh or "
                f"disable grad_comm.")
    bad = []
    for name, spec in sharded_params:
        kind, why = classify_spec(spec, mesh_shape)
        if kind == "bad":
            bad.append(f"{name} ({why})")
    if bad:
        return (f"{src} carries dp-sharded (ZeRO-3, dim 0) and "
                f"mp-sharded param layouts; these param specs fit "
                f"neither form: {bad[:4]}.  Re-shard them via "
                f"partition rules / tp placements, or disable "
                f"grad_comm.")
    return None


def plan_status(plan) -> Tuple[str, Optional[str]]:
    """Activation state of a ShardingPlan's grad_comm spec:
    ``('off', None)`` — no spec, or a 1-device dp axis (nothing crosses
    a wire); ``('active', None)`` — the Executor lowers the stage;
    ``('error', msg)`` — configured but impossible (the Executor raises
    ``msg``; the cost model reports it).  Executor and cost model share
    this predicate so measured and predicted can never disagree about
    WHICH path runs.  Uses the HYBRID compatibility form: {dp, mp}
    meshes and FSDP / mp-sharded params are accepted (the Executor
    gathers them ahead of forward), pp/sp axes and unsupported spec
    shapes reject."""
    cfg = getattr(plan, "grad_comm", None)
    if cfg is None:
        return "off", None
    if dict(plan.mesh.shape).get(DP_AXIS, 1) <= 1:
        return "off", None
    from .sharding import spec_axes
    sharded = [(n, s) for n, s in zip(plan.param_names, plan.param_specs)
               if spec_axes(s)]
    msg = incompatibility(cfg, plan.mesh.shape, sharded, hybrid=True)
    if msg is not None:
        return "error", msg
    return "active", None


# ---------------------------------------------------------------------------
# shared cause strings (Executor raise == shardcheck diagnostic, verbatim)
# ---------------------------------------------------------------------------

def fetch_rule_message(name: str, global_shape, shard_shape) -> str:
    """A fetch neither shard-invariant nor batch-major under dp.  The
    Executor raises this at compile; shardcheck reports it statically —
    one builder so the two can never disagree about the cause."""
    return (f"grad_comm: fetch '{name}' (global {tuple(global_shape)}, "
            f"per-shard {tuple(shard_shape)}) "
            f"is neither shard-invariant nor batch-major — it "
            f"cannot be reconstructed from dp shards.  Fetch "
            f"batch-major or scalar-mean tensors, or disable "
            f"grad_comm.")


def sum_fetch_message(what: str, name: str) -> str:
    """A SUM-reduced loss/fetch under the dp-mean stage — silently off
    by 1/dp.  Shared by the Executor's compile-time numeric probe and
    shardcheck's static reduction classifier."""
    return (f"grad_comm: {what} '{name}' is SUM-reduced over the "
            f"batch — the dp-mean reduction this stage applies "
            f"would silently scale it (and its gradients) by "
            f"1/dp.  Use a mean reduction, or disable "
            f"grad_comm for this program.")


def overlap_note(cfg: "CommSpec", backend: Optional[str] = None) -> str:
    """How the ``overlap`` knob resolves on ``backend`` — the runtime
    lowering (Executor compile record / cost model ``overlap_path``)
    and shardcheck's static report both print this text."""
    if backend is None:
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - backend not initialisable
            backend = "cpu"
    path = resolve_overlap_path(cfg, backend)
    if path == cfg.overlap:
        return (f"grad_comm: overlap={cfg.overlap!r} lowers as "
                f"requested on backend {backend!r}")
    why = ("XLA:CPU executes one thunk at a time, so chunking only "
           "adds rendezvous overhead" if backend == "cpu" else
           "resolved per the latency-hiding scheduler state")
    return (f"grad_comm: overlap={cfg.overlap!r} falls back to the "
            f"{path!r} lowering on backend {backend!r} ({why})")


# ---------------------------------------------------------------------------
# block-scaled int8 quantization
# ---------------------------------------------------------------------------

def quantize_int8_blocks(x, block_size: int):
    """1-D float array -> (int8 blocks ``[nb, B]``, f32 scales
    ``[nb, 1]``).  Pads to a block multiple; scale = absmax/127 per
    block (zero blocks get scale 1 so dequantize is exact zero)."""
    n = x.shape[0]
    pad = (-n) % block_size
    xp = jnp.pad(x.astype(jnp.float32), (0, pad)).reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(xp), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xp / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8_blocks(q, scales, numel: int):
    """Inverse of :func:`quantize_int8_blocks` (drops the padding)."""
    return (q.astype(jnp.float32) * scales).reshape(-1)[:numel]


# ---------------------------------------------------------------------------
# backward production order
# ---------------------------------------------------------------------------

def production_order(program, params, loss_var=None,
                     graph=None) -> List[int]:
    """The order reverse-mode AD finalizes the gradients of ``params``
    (positions into that list), derived from the Program's DefUseGraph.

    A parameter's gradient is complete once the VJPs of *all* the ops
    that consume it have run; a node's VJP runs once the cotangents of
    its outputs exist.  So each node gets a backward level (1 + the max
    level of its consumers, tail nodes at 0) and each param's grad is
    finalized at the max level over its consumers — grads at LOWER
    levels materialize earlier in backward.  This is where the naive
    reverse-creation-order proxy breaks: in a residual/skip
    architecture a shallow branch's param (level close to the loss)
    produces its grad early even when it was recorded late, and a deep
    trunk param recorded early produces late.  Ties (same level) break
    by descending first-use node index, which reduces to the old
    reverse-creation order on straight-line programs.

    Params on no backward path at all (consumed only outside the loss
    cone, or never consumed) get zero grads from ``jax.grad`` — they
    sort last.  Both the Executor's bucket assembly and the cost
    model's ``_comm_block`` call THIS function, so the bucket schedule
    they see is the same by construction.  Pass ``graph`` when a
    DefUseGraph of the program already exists (analyze() builds one
    anyway) to skip the O(nodes) reconstruction."""
    if graph is None:
        from ..static.analysis.graph import DefUseGraph
        graph = DefUseGraph(program)
    n = len(graph.nodes)
    live = None
    if loss_var is not None:
        lv = graph.resolve_fetch(loss_var)
        if lv is not None:
            live = graph.live_nodes([lv])
    # backward level per node: consumers always record after producers
    # (append-only), so one reverse sweep sees every consumer first
    level = [0] * n
    for i in range(n - 1, -1, -1):
        lv = 0
        for v in graph.nodes[i].out_vars:
            for j in graph.consumers_of.get(id(v), ()):
                if live is not None and j not in live:
                    continue
                if level[j] + 1 > lv:
                    lv = level[j] + 1
        level[i] = lv
    # grad of p is finalized at the max level over p's consumers
    grad_level: Dict[int, int] = {}
    first_use: Dict[int, int] = {}
    for i, plist in graph.params_of.items():
        if live is not None and i not in live:
            continue
        for p in plist:
            pid = id(p)
            if level[i] > grad_level.get(pid, -1):
                grad_level[pid] = level[i]
            if i < first_use.get(pid, n):
                first_use[pid] = i
    keyed = []
    for pos, p in enumerate(params):
        gl = grad_level.get(id(p))
        if gl is None:
            keyed.append((1, 0, 0, pos))       # zero-grad: last, stable
        else:
            keyed.append((0, gl, -first_use.get(id(p), 0), pos))
    keyed.sort()
    return [k[-1] for k in keyed]


# ---------------------------------------------------------------------------
# buckets + plan
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Bucket:
    """One fused reduction: which grads it carries (in backward
    production order), how it crosses the wire, and what that costs.
    ``issue_frac`` is the bucket's issue point: the fraction of
    backward (by cumulative grad numel) already complete when this
    bucket's last gradient materializes — the collective can overlap
    the remaining ``1 - issue_frac`` of backward."""
    indices: Tuple[int, ...]      # positions into the grad list
    shapes: Tuple[tuple, ...]
    sizes: Tuple[int, ...]        # numels, aligned with indices
    numel: int
    algorithm: str                # 'psum' | 'scatter' | 'rscatter' | 'none'
    wire_dtype: str               # 'fp32' | 'bf16' | 'int8'
    wire_bytes: int               # per-device bytes per step
    collectives: int
    carries_residual: bool
    issue_frac: float = 1.0

    @property
    def classification(self) -> str:
        # 'rscatter' is the FSDP reduce-scatter-only route: the
        # all-gather leg is skipped because each device keeps exactly
        # its own param shards' grad chunk — bandwidth-class, at half
        # the allreduce wire
        return ("none" if self.algorithm == "none"
                else "bandwidth" if self.algorithm in ("scatter",
                                                       "rscatter")
                else "latency")

    def to_dict(self) -> dict:
        return {
            "params": list(self.indices), "numel": self.numel,
            "algorithm": self.algorithm, "wire_dtype": self.wire_dtype,
            "wire_bytes": self.wire_bytes,
            "collectives": self.collectives,
            "classification": self.classification,
            "error_feedback": self.carries_residual,
            "issue_frac": round(self.issue_frac, 6),
        }


def build_buckets(shapes: Sequence[tuple], fuse_mb: float,
                  order: Optional[Sequence[int]] = None
                  ) -> List[Tuple[Tuple[int, ...], int]]:
    """Greedy bucket assembly over grads in backward production order
    (``order`` — :func:`production_order` — or the reverse of the
    given creation order when None).  Returns ``[(indices, numel)]``;
    every index appears exactly once, each bucket holds at most
    ``fuse_mb`` MB of f32 payload (a single grad larger than the
    budget gets its own bucket)."""
    budget = max(int(float(fuse_mb) * (1 << 20)) // 4, 1)  # f32 elements
    out: List[Tuple[Tuple[int, ...], int]] = []
    cur: List[int] = []
    cur_n = 0
    seq = (list(order) if order is not None
           else list(reversed(range(len(shapes)))))
    for i in seq:
        n = 1
        for d in shapes[i]:
            n *= int(d)
        if cur and cur_n + n > budget:
            out.append((tuple(cur), cur_n))
            cur, cur_n = [], 0
        cur.append(i)
        cur_n += n
    if cur:
        out.append((tuple(cur), cur_n))
    return out


def _padded_numel(numel: int, multiple: int) -> int:
    return int(math.ceil(numel / multiple)) * multiple if multiple > 1 \
        else numel


def _int8_payload(numel: int, dp: int, block_size: int) -> int:
    """One direction's int8 wire payload: values padded so each device
    owns a block-aligned chunk, plus one f32 scale per block.  The ONE
    formula both the scatter-vs-psum threshold and the wire-byte
    accounting use — they must agree or the recorded bytes would not
    match the algorithm actually chosen."""
    np_ = _padded_numel(numel, dp * block_size)
    return np_ * 1 + (np_ // block_size) * _SCALE_BYTES


def _wire_bytes(numel: int, wire_dtype: str, algorithm: str, dp: int,
                block_size: int) -> int:
    """Exact per-device wire bytes of one bucket's reduction under the
    ring model: an allreduce (or its reduce-scatter + all-gather
    decomposition) moves ``2*(dp-1)/dp`` of the payload through every
    device's links per step."""
    if dp <= 1 or algorithm == "none":
        return 0
    one_dir = (dp - 1) / dp
    if algorithm == "rscatter":
        # FSDP reduce-scatter only: each device keeps its own chunk,
        # no all-gather leg — the payload rides ONE direction
        if wire_dtype == "int8":
            payload = _int8_payload(numel, dp, block_size)
        else:
            payload = (_padded_numel(numel, dp)
                       * _WIRE_ITEMSIZE[wire_dtype])
        return int(round(one_dir * payload))
    ring = 2.0 * one_dir
    if wire_dtype == "int8":
        # scatter route: quantized payload + scales ride both directions
        payload = _int8_payload(numel, dp, block_size)
    elif algorithm == "scatter":
        payload = _padded_numel(numel, dp) * _WIRE_ITEMSIZE[wire_dtype]
    else:
        payload = numel * _WIRE_ITEMSIZE[wire_dtype]
    return int(round(ring * payload))


def _gather_wire_bytes(numel: int, size: int) -> int:
    """One forward param all-gather's per-device wire bytes: every
    device receives (and, on the ring path, forwards) ``(size-1)/size``
    of the f32 payload — exactly half the allreduce ring factor, same
    link model as :func:`_wire_bytes`."""
    if size <= 1:
        return 0
    return int(round((size - 1) / size * numel * 4))


def plan_gathers(shapes: Sequence[tuple], kinds: Sequence[tuple],
                 mesh_shape, order: Optional[Sequence[int]] = None
                 ) -> List[dict]:
    """The forward param-gather schedule of the hybrid grad path: one
    all-gather per sharded param (FSDP over 'dp' dim 0, tensor-parallel
    over 'mp' on its sharded dim), emitted in REVERSE backward
    production order — backward level descends toward the loss, so the
    reversed order is forward order and each layer's params are
    requested ahead of that layer's forward (the prefetch shape of the
    overlap stack).  ``kinds[i]`` is ``classify_spec``'s ``(kind, dim)``
    for param i.  Returns ``[{index, axis, size, dim, numel,
    wire_bytes}]`` — static, so the cost model, the wire-byte audit and
    the runtime stats all read the same numbers."""
    shape = dict(mesh_shape)
    seq = (list(order) if order is not None
           else list(range(len(shapes))))
    gathers: List[dict] = []
    for i in reversed(seq):
        kind, dim = kinds[i]
        if kind == "rep":
            continue
        ax = DP_AXIS if kind == "fsdp" else MP_AXIS
        size = int(shape.get(ax, 1))
        numel = int(np.prod(shapes[i])) if shapes[i] else 1
        gathers.append({
            "index": int(i), "axis": ax, "size": size,
            "dim": int(dim or 0), "numel": numel,
            "wire_bytes": _gather_wire_bytes(numel, size)})
    return gathers


def hybrid_layout(plan, named_shapes: Sequence[Tuple[str, tuple]],
                  order: Optional[Sequence[int]] = None):
    """Per-trainable-param comm classification of the hybrid grad path
    plus its forward gather schedule, from ONE source (the plan's
    specs) for the Executor, the cost model and shardcheck alike.

    ``named_shapes`` is ``[(param name, global shape)]`` in creation
    order; ``order`` the backward production order over the same list.
    Returns ``(kinds, fsdp, gathers)`` — ``kinds[i] = (kind, dim)``
    per :func:`classify_spec`, ``fsdp`` the tuple of positions that
    take the reduce-scatter bucket route, ``gathers`` per
    :func:`plan_gathers`.  Raises on specs outside the supported forms
    (callers normally gate via :func:`plan_status` first)."""
    shape = dict(plan.mesh.shape)
    kinds: List[Tuple[str, Optional[int]]] = []
    for name, shp in named_shapes:
        spec = plan.spec_by_name(name)
        kind, dim = classify_spec(spec, shape)
        if kind == "bad":
            raise NotImplementedError(
                f"grad_comm: param '{name}' spec {spec} — {dim}")
        kinds.append((kind, dim))
    fsdp = tuple(i for i, (k, _) in enumerate(kinds) if k == "fsdp")
    gathers = plan_gathers([s for _, s in named_shapes], kinds, shape,
                           order=order)
    return kinds, fsdp, gathers


class GradCommPlan:
    """Static reduction plan: buckets, algorithms, wire bytes.

    Built once per compile from gradient shapes; the in-graph
    :func:`reduce_gradients` follows it exactly, and its byte totals
    are what the Executor reports as ``comm.wire_bytes`` per step and
    the cost model reports as ``predicted_wire_bytes``."""

    __slots__ = ("cfg", "dp", "buckets", "wire_bytes_per_step",
                 "collectives_per_step", "fp32_wire_bytes_per_step",
                 "overlap_path", "gathers", "gather_wire_bytes_per_step",
                 "axis_wire_bytes")

    def __init__(self, cfg: CommSpec, dp: int, buckets: List[Bucket],
                 backend: Optional[str] = None,
                 gathers: Sequence[dict] = ()):
        self.cfg = cfg
        self.dp = int(dp)
        self.buckets = buckets
        self.wire_bytes_per_step = sum(b.wire_bytes for b in buckets)
        self.collectives_per_step = sum(b.collectives for b in buckets)
        # how the overlap knob lowers on THIS backend ('none'/'xla'/
        # 'ring') — recorded on the compile record and consulted by the
        # cost model's exposed-comm simulation, which therefore cannot
        # disagree with what actually compiled
        self.overlap_path = resolve_overlap_path(cfg, backend)
        # forward param-gather schedule (hybrid meshes: FSDP dp-gathers
        # + tensor-parallel mp-gathers; empty on replicated layouts)
        self.gathers = list(gathers)
        self.gather_wire_bytes_per_step = sum(
            g["wire_bytes"] for g in self.gathers)
        # per-mesh-axis wire accounting: grad buckets ride the dp axis;
        # each gather rides its own axis.  The runtime's
        # comm.axis.<name>.wire_bytes stats, the cost model's per-axis
        # prediction and shardcheck's audit all read THIS dict
        axis: Dict[str, int] = {DP_AXIS: self.wire_bytes_per_step}
        for g in self.gathers:
            axis[g["axis"]] = axis.get(g["axis"], 0) + g["wire_bytes"]
        self.axis_wire_bytes = axis
        # the un-quantized, un-bucketed baseline the ratio gates measure
        # against: one fp32 ring allreduce over every gradient byte
        total = sum(b.numel for b in buckets)
        self.fp32_wire_bytes_per_step = _wire_bytes(
            total, "fp32", "psum", self.dp, cfg.block_size)

    @property
    def residual_buckets(self) -> List[Bucket]:
        return [b for b in self.buckets if b.carries_residual]

    def algo_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for b in self.buckets:
            out[b.algorithm] = out.get(b.algorithm, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "dtype": self.cfg.dtype, "dp": self.dp,
            "block_size": self.cfg.block_size,
            "error_feedback": self.cfg.error_feedback,
            "scatter_threshold_KB": self.cfg.scatter_threshold_KB,
            "fuse_grad_size_in_MB": self.cfg.fuse_grad_size_in_MB,
            "overlap": self.cfg.overlap,
            "overlap_path": self.overlap_path,
            "wire_bytes_per_step": self.wire_bytes_per_step,
            "fp32_wire_bytes_per_step": self.fp32_wire_bytes_per_step,
            "collectives_per_step": self.collectives_per_step,
            "gather_wire_bytes_per_step": self.gather_wire_bytes_per_step,
            "axis_wire_bytes": dict(self.axis_wire_bytes),
            "gathers": [dict(g) for g in self.gathers],
            "buckets": [b.to_dict() for b in self.buckets],
        }

    def schedule(self) -> dict:
        """The auditable bucket schedule the compile record carries:
        per bucket — size, algorithm, wire dtype/bytes, issue point —
        plus the overlap knob and the path it resolved to."""
        return {
            "overlap": self.cfg.overlap,
            "path": self.overlap_path,
            "dp": self.dp,
            "wire_bytes_per_step": self.wire_bytes_per_step,
            "axis_wire_bytes": dict(self.axis_wire_bytes),
            "gathers": [dict(g) for g in self.gathers],
            "buckets": [b.to_dict() for b in self.buckets],
        }

    def __repr__(self):
        return (f"GradCommPlan(dtype={self.cfg.dtype}, dp={self.dp}, "
                f"buckets={len(self.buckets)}, "
                f"wire={self.wire_bytes_per_step}B/step "
                f"[fp32 {self.fp32_wire_bytes_per_step}B], "
                f"algos={self.algo_counts()}, "
                f"overlap={self.cfg.overlap}->{self.overlap_path})")


def plan_reduction(shapes: Sequence[tuple], dp: int, cfg: CommSpec,
                   order: Optional[Sequence[int]] = None,
                   backend: Optional[str] = None,
                   fsdp: Sequence[int] = (),
                   gathers: Sequence[dict] = ()) -> GradCommPlan:
    """Assemble buckets over gradient ``shapes`` (creation order;
    ``order`` gives the backward production order — see
    :func:`production_order` — default reverse creation) and pick each
    bucket's wire dtype + collective algorithm.

    ``fsdp`` names the positions whose params are dp-sharded on dim 0
    (ZeRO-3): their grads stay OUT of the gathered buckets and form
    dedicated ``'rscatter'`` buckets — reduce-scatter only, each device
    keeps exactly its own shard's chunk (half the allreduce wire), with
    the per-device EF residual covering the shard-major flat layout.
    ``gathers`` is the forward param-gather schedule
    (:func:`plan_gathers`) that rides the plan for per-axis wire
    accounting."""
    fsdp_set = frozenset(int(i) for i in fsdp)
    seq = (list(order) if order is not None
           else list(reversed(range(len(shapes)))))
    # issue point = fraction of backward (by cumulative grad numel over
    # the FULL production order) complete when the bucket's LAST grad
    # materializes — shared by the interleaved normal/fsdp streams
    numels = [int(np.prod(s)) if s else 1 for s in shapes]
    rank = {i: r for r, i in enumerate(seq)}
    prefix = []
    cum = 0
    for i in seq:
        cum += numels[i]
        prefix.append(cum)
    total_numel = max(cum, 1)

    def _mk(indices, numel, algo, wire, n_coll):
        carries = (cfg.error_feedback and algo != "none"
                   and wire != "fp32")
        last = max(prefix[rank[i]] for i in indices)
        return Bucket(
            indices=indices,
            shapes=tuple(tuple(shapes[i]) for i in indices),
            sizes=tuple(numels[i] for i in indices),
            numel=numel, algorithm=algo, wire_dtype=wire,
            wire_bytes=_wire_bytes(numel, wire, algo, dp,
                                   cfg.block_size),
            collectives=n_coll, carries_residual=carries,
            issue_frac=last / total_numel)

    buckets: List[Bucket] = []
    normal_seq = [i for i in seq if i not in fsdp_set]
    fsdp_seq = [i for i in seq if i in fsdp_set]
    for indices, numel in build_buckets(
            shapes, cfg.fuse_grad_size_in_MB, order=normal_seq):
        if dp <= 1:
            algo, wire = "none", cfg.dtype
        else:
            # threshold compares the QUANTIZED payload (what the
            # scatter route would put on the wire, one direction)
            if cfg.dtype == "int8":
                payload = _int8_payload(numel, dp, cfg.block_size)
            else:
                payload = numel * _WIRE_ITEMSIZE[cfg.dtype]
            if payload >= cfg.scatter_threshold_KB * 1024:
                algo, wire = "scatter", cfg.dtype
            else:
                # latency-bound: one fused psum.  A single-shot int8
                # psum cannot sum payloads carrying per-device scales,
                # so the int8 config's small buckets ride bf16 wire.
                algo = "psum"
                wire = "bf16" if cfg.dtype == "int8" else cfg.dtype
        if algo == "none":
            n_coll = 0
        elif algo == "psum":
            n_coll = 1
        elif wire == "int8":
            n_coll = 4      # all_to_all q, all_to_all scales, ag q, ag s
        else:
            n_coll = 2      # psum_scatter + all_gather
        buckets.append(_mk(indices, numel, algo, wire, n_coll))
    for indices, numel in build_buckets(
            shapes, cfg.fuse_grad_size_in_MB, order=fsdp_seq):
        if dp <= 1:
            buckets.append(_mk(indices, numel, "none", cfg.dtype, 0))
            continue
        # the reduce-scatter IS the point of the FSDP route — there is
        # no psum fallback (a full allreduce would replicate the grad a
        # sharded optimizer state cannot consume).  int8 keeps the
        # one-shot quantized exchange; small int8 buckets ride bf16
        # like the psum route (scales-in-payload has the same
        # constraint either way).
        if cfg.dtype == "int8":
            payload = _int8_payload(numel, dp, cfg.block_size)
            wire = ("int8" if payload >= cfg.scatter_threshold_KB * 1024
                    else "bf16")
        else:
            wire = cfg.dtype
        n_coll = 2 if wire == "int8" else 1   # a2a q + a2a scales | rs
        buckets.append(_mk(indices, numel, "rscatter", wire, n_coll))
    # interleave the two streams back into production order (by issue
    # point) so bucket emission, residual order and the cost model's
    # link simulation all see one schedule
    buckets.sort(key=lambda b: (b.issue_frac,
                                min(rank[i] for i in b.indices)))
    return GradCommPlan(cfg, dp, buckets, backend=backend,
                        gathers=gathers)


# ---------------------------------------------------------------------------
# bucket (dis)assembly — bitwise
# ---------------------------------------------------------------------------

def flatten_bucket(grads: Sequence, bucket: Bucket):
    """Concatenate the bucket's grads into one flat f32 vector (in the
    bucket's production order)."""
    return jnp.concatenate(
        [jnp.asarray(grads[i], jnp.float32).reshape(-1)
         for i in bucket.indices])


def unflatten_bucket(flat, bucket: Bucket, like: Sequence):
    """Split a flat vector back into the bucket's grads — bitwise
    inverse of :func:`flatten_bucket` (shape AND dtype restored from
    ``like``).  Returns ``[(index, grad)]``."""
    out = []
    off = 0
    for i, n, shp in zip(bucket.indices, bucket.sizes, bucket.shapes):
        piece = jax.lax.slice_in_dim(flat, off, off + n).reshape(shp)
        out.append((i, piece.astype(like[i].dtype)))
        off += n
    return out


# -- FSDP reduce-scatter buckets: shard-major flat layout -------------------
# A reduce-scatter bucket's flat layout must align each device's chunk
# with its OWN param shards: row r = the concatenation of every member
# grad's r-th dim-0 shard (flattened).  Rows are padded to a block
# multiple on int8 wire so quantization blocks never straddle a chunk
# boundary; the padding is zeros, quantizes exactly, and is stripped on
# unflatten.  After the reduce-scatter each device's chunk reshapes
# DIRECTLY into its per-param dim-0 shard grads — no gather, no slice.

def fsdp_row_len(bucket: Bucket, dp: int, block_size: int) -> int:
    """Per-device row length of an ``'rscatter'`` bucket's shard-major
    flat layout (``numel/dp``, block-padded on int8 wire)."""
    row = bucket.numel // dp
    if bucket.wire_dtype == "int8":
        row = _padded_numel(row, block_size)
    return row


def bucket_flat_numel(bucket: Bucket, dp: int, block_size: int) -> int:
    """Length of a bucket's flat working vector — and of its EF
    residual: plain ``numel`` for gathered buckets, ``dp x padded-row``
    for FSDP reduce-scatter buckets (the Executor sizes the donated
    residual carry from THIS, re-keyed on the plan fingerprint)."""
    if bucket.algorithm != "rscatter":
        return bucket.numel
    return dp * fsdp_row_len(bucket, dp, block_size)


def flatten_bucket_fsdp(grads: Sequence, bucket: Bucket, dp: int,
                        block_size: int):
    """Shard-major flatten of an ``'rscatter'`` bucket: ``[dp,
    row_len]`` row-major, row r holding every member grad's r-th dim-0
    shard."""
    rows = jnp.concatenate(
        [jnp.asarray(grads[i], jnp.float32).reshape(dp, -1)
         for i in bucket.indices], axis=1)
    row = fsdp_row_len(bucket, dp, block_size)
    pad = row - rows.shape[1]
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    return rows.reshape(-1)


def unflatten_bucket_fsdp(chunk, bucket: Bucket, dp: int,
                          like: Sequence):
    """Split my reduced chunk (one row of the shard-major layout) into
    the bucket's per-param dim-0 SHARD grads — ``[(index, grad)]`` with
    shard shape ``(d0/dp, *rest)``, dtype restored from ``like``."""
    out = []
    off = 0
    for i, n, shp in zip(bucket.indices, bucket.sizes, bucket.shapes):
        ln = n // dp
        piece = jax.lax.slice_in_dim(chunk, off, off + ln)
        piece = piece.reshape((int(shp[0]) // dp,) + tuple(shp[1:]))
        out.append((i, piece.astype(like[i].dtype)))
        off += ln
    return out


# ---------------------------------------------------------------------------
# in-graph reduction (call INSIDE shard_map over the dp axis)
# ---------------------------------------------------------------------------

def _rs_ag(x, axis_name: str, dp: int):
    """Bandwidth route for non-int8 wire: pad to a dp multiple,
    psum_scatter (each device reduces its chunk), all_gather back."""
    n = x.shape[0]
    np_ = _padded_numel(n, dp)
    xp = jnp.pad(x, (0, np_ - n))
    chunk = jax.lax.psum_scatter(xp, axis_name, scatter_dimension=0,
                                 tiled=True)
    return jax.lax.all_gather(chunk, axis_name, tiled=True)[:n]


# -- ppermute-chunked ring collectives (the explicit overlap path) ----------
# Each step moves ONE chunk through one ppermute: small independent
# collectives the scheduler can slot between backward ops even without
# latency-hiding support, instead of one monolithic fused collective it
# must either hoist whole or leave after backward.  Wire bytes are
# identical to the fused route (every device still sends (dp-1)/dp of
# the payload per direction), so the plan's byte accounting holds for
# both paths.

def _chunked_all_to_all(rows, axis_name: str, dp: int):
    """``lax.all_to_all`` decomposed into ``dp-1`` single-chunk
    ppermutes.  ``rows[k]`` is the chunk destined for device k; returns
    the same ``[dp, ...]`` layout all_to_all produces (row k = the
    chunk device k sent here), via one roll by axis index — received
    chunks arrive in ascending cyclic source order by schedule."""
    idx = jax.lax.axis_index(axis_name)
    got = [jnp.take(rows, idx, axis=0)]          # my own contribution
    for s in range(1, dp):
        perm = [(d, (d - s) % dp) for d in range(dp)]
        # device d sends rows[(d - s) % dp]; receiver r then gets, from
        # source (r + s) % dp, exactly the chunk destined for r
        sent = jnp.take(rows, (idx - s) % dp, axis=0)
        got.append(jax.lax.ppermute(sent, axis_name, perm))
    # got[s] came from source (idx + s) % dp -> roll restores row k =
    # source k, the all_to_all layout
    return jnp.roll(jnp.stack(got), idx, axis=0)


def _chunked_all_gather(chunk, axis_name: str, dp: int):
    """``lax.all_gather`` decomposed into ``dp-1`` single-chunk
    ppermutes: every device broadcasts its own (reduced) chunk, one
    peer per step.  Returns ``[dp, ...]`` with row k = device k's
    chunk — the tiled all_gather layout after a reshape."""
    idx = jax.lax.axis_index(axis_name)
    got = [chunk]
    for s in range(1, dp):
        perm = [(d, (d - s) % dp) for d in range(dp)]
        got.append(jax.lax.ppermute(chunk, axis_name, perm))
    return jnp.roll(jnp.stack(got), idx, axis=0)


def _ascending_sum(rows, dp: int):
    """Left-to-right fold over ``rows[0..dp-1]`` — accumulation in
    ascending absolute device order, which is bitwise-identical to what
    XLA's psum/psum_scatter computes (property-tested), so the ring
    path can never change fp32 training numerics."""
    total = rows[0]
    for k in range(1, dp):
        total = total + rows[k]
    return total


def _rs_ag_ring(x, axis_name: str, dp: int):
    """The ppermute-chunked ring form of :func:`_rs_ag`: chunked
    all_to_all -> ascending-order local reduction of my chunk ->
    chunked all_gather.  Bitwise-equal to ``_rs_ag`` at fp32."""
    n = x.shape[0]
    np_ = _padded_numel(n, dp)
    rows = jnp.pad(x, (0, np_ - n)).reshape(dp, np_ // dp)
    total = _ascending_sum(_chunked_all_to_all(rows, axis_name, dp), dp)
    return _chunked_all_gather(total, axis_name, dp).reshape(-1)[:n]


def _rs_only(x, axis_name: str, dp: int, ring: bool):
    """Reduce-scatter WITHOUT the all-gather leg: my chunk of the sum.
    ``x`` length must be a dp multiple (the shard-major FSDP layout
    guarantees it).  The ring form's ascending accumulation is
    bitwise-identical to ``psum_scatter`` at fp32 (same property as
    :func:`_ascending_sum` vs psum), so flipping the overlap knob can
    never change FSDP training numerics."""
    if ring:
        rows = x.reshape(dp, x.shape[0] // dp)
        return _ascending_sum(
            _chunked_all_to_all(rows, axis_name, dp), dp)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                tiled=True)


def gather_param(shard, axis_name: str, size: int, dim: int = 0,
                 ring: bool = False):
    """All-gather one sharded param to its full value inside shard_map
    — the forward-prefetch leg of the hybrid grad path (FSDP shards
    gather over 'dp' on dim 0, tensor-parallel shards over 'mp' on
    their sharded dim).  ``ring=True`` decomposes into ``size-1``
    single-chunk ppermutes (:func:`_chunked_all_gather`) so even a
    static scheduler can slot the steps between forward ops; the fused
    form leaves one ``all_gather`` for the latency-hiding scheduler.
    Wire bytes either way: ``(size-1)/size`` of the payload
    (:func:`_gather_wire_bytes`)."""
    if size <= 1:
        return shard
    if dim != 0:
        moved = jnp.moveaxis(shard, dim, 0)
        return jnp.moveaxis(
            gather_param(moved, axis_name, size, 0, ring=ring), 0, dim)
    if ring:
        rows = _chunked_all_gather(shard, axis_name, size)
        return rows.reshape((size * shard.shape[0],) + shard.shape[1:])
    return jax.lax.all_gather(shard, axis_name, tiled=True)


def _reduce_int8_scatter(carry, axis_name: str, dp: int, block: int,
                         error_feedback: bool, ring: bool = False,
                         sentry: bool = False, step=None,
                         bucket_label: str = ""):
    """The two-shot block-scaled int8 reduction.  ``carry`` is the
    residual-corrected local gradient (flat f32).  Returns (reduced sum
    as f32, per-device residual or None, nonfinite-block count or
    None).  ``ring=True`` decomposes both shots into single-chunk
    ppermutes (same wire bytes, ascending accumulation order) so each
    step is independently schedulable.

    ``sentry=True`` is the quantize-time guard: a single non-finite
    value would otherwise poison its whole block's max-abs scale (the
    failure class EQuARX's scale handling exists to avoid) AND the
    error-feedback residual, which then carries the corruption into
    future steps.  With the sentry on, non-finite values are detected
    *before* quantization — the count of poisoned blocks feeds the
    anomaly flag — and masked to zero so the wire payload and the
    residual stay finite (the flagged step's update is discarded by
    the sentry select anyway, so masking never changes training
    numerics).  ``step``/``bucket_label`` feed the in-graph
    ``grad_comm.wire`` corruption point (testing/fault.py)."""
    n = carry.shape[0]
    np_ = _padded_numel(n, dp * block)
    chunk = np_ // dp
    cb = chunk // block
    nonfinite_blocks = None
    if sentry:
        finite = jnp.isfinite(carry)
        padded_bad = jnp.pad(~finite, (0, np_ - n))
        nonfinite_blocks = jnp.sum(
            jnp.any(padded_bad.reshape(-1, block), axis=1)
            .astype(jnp.int32))
        carry = jnp.where(finite, carry, 0.0)
    # shot 1: quantize local, exchange chunks (int8 + scales on wire)
    q, s = quantize_int8_blocks(jnp.pad(carry, (0, np_ - n)), block)
    if step is not None:
        from ..testing import fault
        q = fault.corrupt_in_graph("grad_comm.wire", q, step,
                                   tensor=f"{bucket_label}.q")
        s = fault.corrupt_in_graph("grad_comm.wire", s, step,
                                   tensor=f"{bucket_label}.scales")
    if ring:
        qq = _chunked_all_to_all(q.reshape(dp, cb, block), axis_name, dp)
        ss = _chunked_all_to_all(s.reshape(dp, cb, 1), axis_name, dp)
        red_chunk = _ascending_sum(
            qq.astype(jnp.float32) * ss, dp).reshape(-1)
    else:
        qq = jax.lax.all_to_all(q.reshape(dp, cb, block), axis_name, 0, 0)
        ss = jax.lax.all_to_all(s.reshape(dp, cb, 1), axis_name, 0, 0)
        # dequantize per peer, sum in f32: my chunk of the global sum
        red_chunk = jnp.sum(qq.astype(jnp.float32) * ss,
                            axis=0).reshape(-1)
    wire_nf = None
    if sentry:
        # guard the RECEIVED payload too: a corrupted wire value would
        # otherwise be laundered by the requantize below (NaN absmax
        # reads as scale 1 and int8-casts to 0 — silently wrong, and
        # its requantize error would poison the residual forever).
        # Count it (device-varying chunk -> psum so the flag agrees)
        # and mask it; the flagged step's update is discarded anyway.
        bad = ~jnp.isfinite(red_chunk)
        wire_nf = jax.lax.psum(jnp.sum(bad.astype(jnp.int32)),
                               axis_name)
        red_chunk = jnp.where(bad, 0.0, red_chunk)
    # shot 2: requantize the reduced chunk, gather (int8 + scales)
    q2, s2 = quantize_int8_blocks(red_chunk, block)
    if ring:
        qg = _chunked_all_gather(q2.reshape(-1), axis_name, dp)
        sg = _chunked_all_gather(s2.reshape(-1), axis_name, dp)
    else:
        qg = jax.lax.all_gather(q2.reshape(-1), axis_name, tiled=True)
        sg = jax.lax.all_gather(s2.reshape(-1), axis_name, tiled=True)
    total = dequantize_int8_blocks(qg.reshape(-1, block),
                                   sg.reshape(-1, 1), n)
    if not error_feedback:
        return total, None, nonfinite_blocks, wire_nf
    # residual: my local quantize error everywhere, PLUS the requantize
    # error on the chunk I own (I am the only device that knows it; the
    # next step's psum recovers it exactly once)
    e1 = jnp.pad(carry, (0, np_ - n)) - dequantize_int8_blocks(q, s, np_)
    e2 = red_chunk - dequantize_int8_blocks(q2, s2, chunk)
    idx = jax.lax.axis_index(axis_name)
    own = jax.lax.dynamic_slice(e1, (idx * chunk,), (chunk,))
    e1 = jax.lax.dynamic_update_slice(e1, own + e2, (idx * chunk,))
    return total, e1[:n], nonfinite_blocks, wire_nf


def _reduce_int8_rscatter(carry, axis_name: str, dp: int, block: int,
                          error_feedback: bool, ring: bool = False,
                          sentry: bool = False, step=None,
                          bucket_label: str = ""):
    """One-shot block-scaled int8 reduce-scatter for FSDP buckets:
    quantize the shard-major flat, exchange chunks, dequantize-sum —
    each device keeps its OWN chunk (its params' shard rows), so the
    second shot (requantize + all-gather) never happens and neither
    does its wire or its requantize error.  Returns (my reduced chunk
    f32, per-device residual or None, nonfinite-block count, wire_nf).
    The EF residual is the full-length local quantize error e1 — the
    requantize term e2 of the gathered route has no analog here."""
    n = carry.shape[0]            # dp*block multiple by layout
    chunk = n // dp
    cb = chunk // block
    nonfinite_blocks = None
    if sentry:
        finite = jnp.isfinite(carry)
        nonfinite_blocks = jnp.sum(
            jnp.any((~finite).reshape(-1, block), axis=1)
            .astype(jnp.int32))
        carry = jnp.where(finite, carry, 0.0)
    q, s = quantize_int8_blocks(carry, block)
    if step is not None:
        from ..testing import fault
        q = fault.corrupt_in_graph("grad_comm.wire", q, step,
                                   tensor=f"{bucket_label}.q")
        s = fault.corrupt_in_graph("grad_comm.wire", s, step,
                                   tensor=f"{bucket_label}.scales")
    if ring:
        qq = _chunked_all_to_all(q.reshape(dp, cb, block), axis_name, dp)
        ss = _chunked_all_to_all(s.reshape(dp, cb, 1), axis_name, dp)
        red_chunk = _ascending_sum(
            qq.astype(jnp.float32) * ss, dp).reshape(-1)
    else:
        qq = jax.lax.all_to_all(q.reshape(dp, cb, block), axis_name, 0, 0)
        ss = jax.lax.all_to_all(s.reshape(dp, cb, 1), axis_name, 0, 0)
        red_chunk = jnp.sum(qq.astype(jnp.float32) * ss,
                            axis=0).reshape(-1)
    wire_nf = None
    if sentry:
        # same wire guard as the two-shot route: corrupted received
        # payload is counted (psum'd — chunks are device-varying) and
        # masked; the flagged step's update is discarded anyway
        bad = ~jnp.isfinite(red_chunk)
        wire_nf = jax.lax.psum(jnp.sum(bad.astype(jnp.int32)),
                               axis_name)
        red_chunk = jnp.where(bad, 0.0, red_chunk)
    if not error_feedback:
        return red_chunk, None, nonfinite_blocks, wire_nf
    e1 = carry - dequantize_int8_blocks(q, s, n)
    return red_chunk, e1, nonfinite_blocks, wire_nf


def _reduce_bucket_fsdp(flat, residual, axis_name: str, bucket: Bucket,
                        plan: GradCommPlan, ring: bool = False,
                        sentry: bool = False, step=None,
                        bucket_label: str = ""):
    """Reduce one FSDP (``'rscatter'``) bucket: the shard-major flat
    reduce-scatters over dp and each device keeps its own chunk —
    returns (my mean chunk f32, new residual or None, nonfinite-block
    count or None, wire_nf or None).  fp32 wire is exact (residual
    drains); bf16 carries ``carry - sent``; int8 takes the one-shot
    quantized exchange above."""
    dp = plan.dp
    carry = flat + residual if residual is not None else flat
    wire = bucket.wire_dtype
    if wire == "fp32":
        chunk = _rs_only(carry, axis_name, dp, ring)
        new_res = residual
        if residual is not None:
            new_res = jnp.zeros_like(residual)
        return chunk / dp, new_res, None, None
    if wire == "bf16":
        sent = carry.astype(jnp.bfloat16)
        chunk = _rs_only(sent, axis_name, dp, ring).astype(jnp.float32)
        new_res = (carry - sent.astype(jnp.float32)
                   if bucket.carries_residual and residual is not None
                   else None)
        return chunk / dp, new_res, None, None
    chunk, new_res, nfb, wire_nf = _reduce_int8_rscatter(
        carry, axis_name, dp, plan.cfg.block_size,
        bucket.carries_residual and residual is not None, ring=ring,
        sentry=sentry, step=step, bucket_label=bucket_label)
    return chunk / dp, new_res, nfb, wire_nf


def _reduce_bucket(flat, residual, axis_name: str, bucket: Bucket,
                   plan: GradCommPlan, ring: bool = False,
                   sentry: bool = False, step=None,
                   bucket_label: str = ""):
    """Reduce one flat bucket over the dp axis following the plan.
    Returns (mean-reduced f32 vector, new residual or None,
    nonfinite-block count or None).  ``ring`` lowers the bandwidth
    route as ppermute chunks; latency-bound psum buckets stay one
    fused psum on every path (chunking a small bucket would multiply
    its latency, the thing the threshold protects).  ``'rscatter'``
    buckets return each device's OWN chunk (FSDP shard grads), not the
    replicated mean."""
    dp = plan.dp
    if bucket.algorithm == "none":
        return flat, residual, None, None
    if bucket.algorithm == "rscatter":
        return _reduce_bucket_fsdp(
            flat, residual, axis_name, bucket, plan, ring=ring,
            sentry=sentry, step=step, bucket_label=bucket_label)
    carry = flat + residual if residual is not None else flat
    wire = bucket.wire_dtype
    rs = _rs_ag_ring if ring else _rs_ag
    if wire == "fp32":
        total = (jax.lax.psum(carry, axis_name)
                 if bucket.algorithm == "psum"
                 else rs(carry, axis_name, dp))
        new_res = residual
        if residual is not None:  # fp32 wire is exact: residual drains
            new_res = jnp.zeros_like(residual)
        return total / dp, new_res, None, None
    if wire == "bf16":
        sent = carry.astype(jnp.bfloat16)
        total = (jax.lax.psum(sent, axis_name)
                 if bucket.algorithm == "psum"
                 else rs(sent, axis_name, dp)).astype(jnp.float32)
        new_res = (carry - sent.astype(jnp.float32)
                   if bucket.carries_residual and residual is not None
                   else None)
        return total / dp, new_res, None, None
    total, new_res, nfb, wire_nf = _reduce_int8_scatter(
        carry, axis_name, dp, plan.cfg.block_size,
        bucket.carries_residual and residual is not None, ring=ring,
        sentry=sentry, step=step, bucket_label=bucket_label)
    return total / dp, new_res, nfb, wire_nf


def reduce_gradients(grads: Sequence, *, plan: GradCommPlan,
                     axis_name: str = DP_AXIS,
                     residuals: Optional[Sequence] = None,
                     mode: Optional[str] = None,
                     sentry: bool = False, step=None):
    """Reduce per-shard gradients to their dp-mean following ``plan``.

    Must be called INSIDE a ``shard_map`` over ``axis_name``: ``grads``
    are the local (device-varying) gradients, one entry per trainable
    param in creation order.  ``residuals`` is the per-device error-
    feedback carry — one flat f32 vector per ``plan.residual_buckets``
    entry, in plan order — or None to reduce without error feedback
    (the residual-less SpmdTrainStep path).

    ``mode`` is the overlap lowering (default: the plan's resolved
    ``overlap_path``): ``'none'`` puts an ``optimization_barrier``
    between backward and the comm stage — every bucket waits for every
    grad, the measured no-overlap baseline; ``'xla'`` emits each
    bucket's fused collective dependent only on its own grads, early
    enough in the HLO for the latency-hiding scheduler to split it
    into async start/done around the remaining backward; ``'ring'``
    additionally chunks the bandwidth-route collectives into
    single-chunk ppermute steps any scheduler can interleave.

    ``sentry=True`` additionally returns the in-graph anomaly sentry's
    per-bucket scan — one reduction per bucket over the already-built
    flat views, never one per param: ``{"pre": [nb] int32`` (non-finite
    elements in the *local* pre-reduction grads, psum'd over dp so
    every replica agrees), ``"post": [nb] int32`` (non-finite in the
    reduced result — a corrupted wire payload lands here), ``"blocks":
    int32`` (int8 blocks whose max-abs scale a non-finite value would
    have poisoned, psum'd; the quantizer masks them — see
    ``_reduce_int8_scatter``), ``"norm2": f32}`` (sum of squared
    reduced grads — the global grad-norm stat, and an overflow canary:
    a finite-but-huge corruption drives it to inf).  ``step`` (the
    executable's traced step counter) activates the in-graph
    ``grad_comm.wire`` corruption point for chaos drills.

    Returns ``(reduced grads, new residuals)`` — plus the sentry dict
    when ``sentry=True``; reduced grads come back replicated (every
    device holds the same mean) in the original order/shape/dtype —
    EXCEPT params in ``'rscatter'`` (FSDP) buckets, whose entries are
    each device's own dim-0 SHARD of the mean grad (shape
    ``(d0/dp, *rest)``): the caller's shard_map out_spec ``P(dp)``
    reassembles them as the dp-sharded global grad the sharded
    optimizer state consumes.  Buckets are emitted in backward
    production order, each as an independent collective, so bucket N's
    reduction can overlap the producers of the buckets after it."""
    mode = plan.overlap_path if mode is None else mode
    if mode == "none":
        # all buckets depend on ALL grads: the comm stage cannot start
        # until backward is complete (exposed comm == total comm)
        grads = list(jax.lax.optimization_barrier(tuple(grads)))
    out = list(grads)
    new_res: List = []
    pre_nf: List = []
    post_nf: List = []
    blocks = jnp.asarray(0, jnp.int32) if sentry else None
    norm2 = jnp.asarray(0.0, jnp.float32) if sentry else None
    ri = 0
    for bi, bucket in enumerate(plan.buckets):
        res = None
        if residuals is not None and bucket.carries_residual:
            res = residuals[ri]
        fsdp = bucket.algorithm == "rscatter"
        flat = (flatten_bucket_fsdp(grads, bucket, plan.dp,
                                    plan.cfg.block_size)
                if fsdp else flatten_bucket(grads, bucket))
        if sentry:
            pre_nf.append(jnp.sum(
                (~jnp.isfinite(flat)).astype(jnp.int32)))
        red, r2, nfb, wire_nf = _reduce_bucket(
            flat, res, axis_name, bucket, plan, ring=(mode == "ring"),
            sentry=sentry, step=step, bucket_label=f"bucket.{bi}")
        if sentry:
            # the reduced flat is replicated, so one count per bucket
            # is already mesh-agreed (wire_nf — corruption caught in
            # the received int8 chunks before the requantize launders
            # it — arrives already psum'd); pre counts + block counts
            # are device-varying and psum below.  An rscatter bucket's
            # reduced CHUNK is device-varying too (each device holds
            # its own shard rows), so its post count and its norm
            # contribution psum here — the flag stays mesh-agreed on
            # hybrid meshes and the norm matches the gathered path's.
            post = jnp.sum((~jnp.isfinite(red)).astype(jnp.int32))
            nrm = jnp.sum(red * red)
            if fsdp:
                post = jax.lax.psum(post, axis_name)
                nrm = jax.lax.psum(nrm, axis_name)
            if wire_nf is not None:
                post = post + wire_nf
            post_nf.append(post)
            norm2 = norm2 + nrm
            if nfb is not None:
                blocks = blocks + nfb
        if residuals is not None and bucket.carries_residual:
            new_res.append(r2 if r2 is not None
                           else jnp.zeros_like(flat))
            ri += 1
        pieces = (unflatten_bucket_fsdp(red, bucket, plan.dp, grads)
                  if fsdp else unflatten_bucket(red, bucket, grads))
        for i, g in pieces:
            out[i] = g
    if not sentry:
        return out, new_res
    info = {
        "pre": jax.lax.psum(jnp.stack(pre_nf), axis_name),
        "post": jnp.stack(post_nf),
        "blocks": jax.lax.psum(blocks, axis_name),
        "norm2": norm2,
    }
    return out, new_res, info
