"""paddle_tpu.distributed — SPMD distribution over jax.sharding.Mesh
(reference: python/paddle/distributed/ — SURVEY §2.2/§2.3: the c_* op zoo,
NCCLCommContext rings and TCP bootstrap collapse into named mesh axes +
lax collectives + jax.distributed.initialize)."""
from . import fleet as _fleet_mod  # noqa: F401
from .collective import (Group, ReduceOp, all_gather,  # noqa: F401
                         all_gather_object, all_reduce, alltoall, barrier,
                         broadcast, collective_permute, get_group, in_spmd,
                         new_group, recv, reduce, reduce_scatter, scatter,
                         send, shift, spmd)
from .env import (ParallelEnv, get_rank, get_world_size,  # noqa: F401
                  early_init, init_parallel_env, is_initialized)
from .fleet import Fleet, fleet  # noqa: F401
from .mesh import (DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS, axis_size,  # noqa
                   ensure_mesh, get_mesh, init_mesh, mesh_users,
                   named_sharding, register_mesh_user,
                   release_mesh_user, set_mesh)
from .strategy import DistributedStrategy  # noqa: F401
# `paddle_tpu.distributed.sharding` is the GSPMD sharding subsystem
# (rule engine, plans, reshardable checkpoint state);
# `paddle_tpu.distributed.grad_comm` is the quantized/bucketed
# gradient-collective stage (strategy.grad_comm knobs);
# `paddle_tpu.distributed.supervisor` is the self-healing layer that
# keeps a training entrypoint alive (hang watchdog, elastic restart);
# `paddle_tpu.distributed.anomaly` is its data-plane counterpart (the
# escalation ladder over the in-graph anomaly sentry)
from . import anomaly  # noqa: F401
from . import grad_comm  # noqa: F401
from . import sharding  # noqa: F401
from . import supervisor  # noqa: F401
from .anomaly import AnomalyEscalation, AnomalyPolicy  # noqa: F401
from .sharding import (ShardedState, ShardingPlan,  # noqa: F401
                       SpecLayout, gather_tree, match_partition_rules,
                       plan_for_params, shard_tree, spec_divisor,
                       specs_for_state, with_constraint)
from .supervisor import (ProcessSupervisor, ServingSupervisor,  # noqa: F401
                         StepWatchdog, SupervisorGaveUp,
                         SupervisorResult, TrainingSupervisor)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity (reference: collective.py:809)."""
    from ..parallel.tp_layers import split as _split
    return _split(x, size, operation, axis, num_partitions, gather_out,
                  weight_attr, bias_attr, name)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py.  On TPU the SPMD model needs no
    process-per-device: run func once; the mesh spans all devices."""
    func(*args)


def launch():
    raise NotImplementedError(
        "paddle.distributed.launch: single-controller SPMD needs no "
        "per-device process launcher; for multi-host, start one process "
        "per host with COORDINATOR_ADDRESS/PADDLE_TRAINER_ID set and call "
        "init_parallel_env().")
