"""Fleet — the distributed-training facade.

Reference: python/paddle/distributed/fleet/ (Fleet fleet_base.py:63: init
:130, distributed_optimizer :598, distributed_model :649, minimize
:1078-1202 meta-optimizer composition; RoleMaker role_maker.py:528).

TPU-native compilation of the strategy: instead of rewriting ProgramDescs
through chained meta-optimizers, ``distributed_optimizer``/
``distributed_model`` record the strategy, and ``get_train_step`` compiles
ONE SpmdTrainStep whose mesh shape + shardings realise the same
capabilities (amp → autocast+scaler; recompute → jax.checkpoint; sharding →
ZeRO shardings; tensor_parallel → 'mp' axis; pipeline → 'pp' axis;
gradient_merge → microbatch accumulation loop; lamb/lars → optimizer swap).
"""
from __future__ import annotations

import os
from typing import Optional

from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from .mesh import ensure_mesh, get_mesh, init_mesh
from .strategy import DistributedStrategy


class _RoleMaker:
    """reference: role_maker.py PaddleCloudRoleMaker (env parsing)."""

    def __init__(self, is_collective=True):
        self.is_collective = is_collective

    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return get_rank() == 0


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._role_maker: Optional[_RoleMaker] = None
        self._optimizer = None
        self._user_optimizer = None
        self._model = None
        self._train_step = None

    # -- lifecycle (fleet_base.py:130) ------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._role_maker = role_maker or _RoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        import jax
        cur = get_mesh()
        if cur is not None:
            # respect a user-pinned live mesh when it satisfies the
            # strategy's model-parallel degrees (a subset mesh on a
            # bigger host is a legitimate pin — re-deriving over ALL
            # devices here would fight init_mesh and trip the
            # replace guard against live compiled programs)
            try:
                want = self._strategy.infer_mesh_shape(
                    int(cur.devices.size))
            except Exception:  # degrees don't fit the pinned mesh
                want = None
            from .mesh import MP_AXIS, PP_AXIS, SP_AXIS
            cur_shape = dict(cur.shape)
            if want is not None and all(
                    cur_shape.get(a, 1) == want.get(a, 1)
                    for a in (MP_AXIS, PP_AXIS, SP_AXIS)):
                init_parallel_env(cur_shape)
                return self
        n = len(jax.devices())
        mesh_shape = self._strategy.infer_mesh_shape(n)
        init_parallel_env(mesh_shape)
        return self

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_num(self):
        return self._role_maker.worker_num()

    def worker_index(self):
        return self._role_maker.worker_index()

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def worker_endpoints(self, to_string=False):
        eps = ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .collective import barrier
        barrier()

    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        raise NotImplementedError(
            "Parameter-server mode: on TPU the PS capability is provided by "
            "mesh-sharded embedding tables (paddle_tpu.parallel tp_layers) "
            "— see SURVEY §7 'Sparse/PS capability'.")

    def stop_worker(self):
        pass

    # -- strategy compilation (fleet_base.py:598,649,1078) -----------------
    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._user_optimizer = optimizer
        opt = optimizer
        s = self._strategy or DistributedStrategy()
        # fail loudly on strategies this build deliberately re-architects
        # away (VERDICT r3: silent no-op toggles are worse than missing),
        # and on parallel degrees that don't divide the device count
        import jax
        from .strategy import validate_toggles
        mesh = get_mesh()
        validate_toggles(s, n_devices=(int(mesh.devices.size)
                                       if mesh is not None
                                       else len(jax.devices())))
        if s.lamb:
            from ..optimizer import Lamb
            if not isinstance(opt, Lamb):
                opt = Lamb(learning_rate=opt._learning_rate,
                           lamb_weight_decay=s.lamb_configs.lamb_weight_decay,
                           parameters=opt._parameter_list,
                           grad_clip=opt._grad_clip)
        elif s.lars:
            from ..optimizer import LarsMomentum
            if not isinstance(opt, LarsMomentum):
                opt = LarsMomentum(
                    learning_rate=opt._learning_rate,
                    lars_coeff=s.lars_configs.lars_coeff,
                    lars_weight_decay=s.lars_configs.lars_weight_decay,
                    parameters=opt._parameter_list,
                    grad_clip=opt._grad_clip)
        # the static Executor reads the strategy off the optimizer when
        # minimize() attaches it to a Program, and lowers the donated
        # _ExecState through jit-with-shardings on the strategy's mesh
        # (distributed/sharding.py ShardingPlan)
        opt._dist_strategy = s
        self._optimizer = opt
        return opt

    def distributed_model(self, model):
        from ..parallel.data_parallel import DataParallel
        self._model = model
        return DataParallel(model)

    def get_train_step(self, model, loss_fn, optimizer=None, n_inputs=1):
        """Compile the strategy into one SpmdTrainStep (the meta-optimizer
        chain's terminal 'graph execution' stage, fleet_base.py:1191).
        strategy.localsgd / adaptive_localsgd route to the vmapped
        per-replica LocalSGDTrainStep instead."""
        opt = optimizer or self._optimizer
        s = self._strategy or DistributedStrategy()
        if s.localsgd or s.adaptive_localsgd:
            from ..parallel.localsgd import LocalSGDTrainStep
            step = LocalSGDTrainStep(model, loss_fn, opt,
                                     mesh=ensure_mesh(), strategy=s,
                                     n_inputs=n_inputs,
                                     adaptive=s.adaptive_localsgd)
        else:
            from ..parallel.spmd_train_step import SpmdTrainStep
            step = SpmdTrainStep(model, loss_fn, opt, mesh=ensure_mesh(),
                                 strategy=s, n_inputs=n_inputs,
                                 donate=True)
        self._train_step = step
        return step

    def _sync_step_params(self):
        """Pull authoritative weights out of the compiled step before any
        persistence read (LocalSGD replicas / ZeRO-3 padded shards)."""
        step = getattr(self, "_train_step", None)
        if step is not None:
            step.sync_params()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        assert self._optimizer is not None, "call distributed_optimizer first"
        return self._optimizer.minimize(loss)

    # -- persistence (fleet_base.py:550) ----------------------------------
    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        import paddle_tpu as paddle
        self._sync_step_params()
        if self._model is not None and dirname:
            paddle.save(self._model.state_dict(),
                        os.path.join(dirname, "model.pdparams"))

    def save_inference_model(self, executor=None, dirname=None,
                             feeded_var_names=None, target_vars=None,
                             main_program=None, export_for_deployment=True,
                             model=None, input_spec=None):
        """Export a serveable artifact (reference fleet_base.py:550 →
        save_inference_model).  Delegates to ``paddle.jit.save`` — the
        StableHLO artifact the Predictor consumes.  Pass ``model`` +
        ``input_spec`` (or call ``distributed_model`` first and give the
        model a traced ``forward``)."""
        model = model or self._model
        if model is None:
            raise ValueError(
                "fleet.save_inference_model: no model registered — call "
                "fleet.distributed_model(model) first or pass model=...")
        self._sync_step_params()
        if dirname is None:
            raise ValueError("fleet.save_inference_model: dirname required")
        from .. import jit as pjit
        # unwrap DataParallel shells
        inner = getattr(model, "_layers", model)
        path = os.path.join(dirname, "model")
        pjit.save(inner, path, input_spec=input_spec)
        return path

    @property
    def util(self):
        return _FleetUtil()


class _FleetUtil:
    def all_reduce(self, input, mode="sum"):
        return input

    def barrier(self, comm_world="worker"):
        from .collective import barrier
        barrier()

    def get_file_shard(self, files):
        w = get_world_size()
        i = get_rank()
        return files[i::w]


# fleet-side dataset entry points (reference: fleet.DatasetFactory)
from ..io.dataset_dist import (DatasetFactory, InMemoryDataset,  # noqa: E402
                               QueueDataset)

fleet = Fleet()
