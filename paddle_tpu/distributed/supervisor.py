"""Supervised elastic training: hang detection, kill, restart, reshard.

The reference framework's fleet runtime assumes an agent that notices
dead or wedged trainers and restarts them (reference:
distributed/fleet/elastic/ — the elastic manager watches heartbeats and
relaunches the local trainer).  This repo has every recovery *rail*
already — fault injection + digest-verified :class:`SnapshotStore`
(PR 3), reshard-on-restore (PR 8), per-executable ``predicted_step_s``
(PR 9), step-cadence snapshots (this PR) — but until now no *actor*
closed the loop: a hung collective or a crashed worker wedged the job
until a human intervened.

:class:`TrainingSupervisor` is that actor.  It runs the training
entrypoint in a child process and keeps it alive end-to-end:

* The child stamps a :class:`HeartbeatWriter` beat on every Executor
  step (one ``obs_hook``-style module check — zero cost when
  unsupervised).  Each beat carries the wall time, the step counter,
  the compile record's ``predicted_step_s`` and the observed interval
  since the previous beat, checksummed against torn reads.
* The parent's :class:`StepWatchdog` derives a per-step deadline from
  ``predicted_step_s`` with a drift-aware multiplier (observed median /
  predicted, clamped), falling back to a rolling p99 of observed step
  times when no prediction exists.  Hangs — not just crashes — are the
  dominant failure mode once collectives overlap compute (T3,
  PAPERS.md): a deadlocked all-reduce never raises, it just stops
  beating.
* A missed deadline escalates SIGTERM → SIGKILL.  SIGTERM first, so a
  *slow* child can still save at the next step boundary and exit
  cleanly (``TrainEpochRange`` preemption semantics); a truly wedged
  child ignores it and eats the SIGKILL after ``hang_grace_s``.
* Every exit that isn't a clean ``0`` restarts the child with
  exponential backoff, bounded by a crash-loop budget (``crash_budget``
  failures inside ``crash_window_s`` → :class:`SupervisorGaveUp`
  carrying the full ``exit_history``) and a total ``max_restarts`` cap.
* On restart the entrypoint runs fresh: it re-detects the visible
  device count and resumes from the newest intact snapshot through the
  existing ``SnapshotStore``/``ShardedState`` reshard path — losing
  devices (mesh 8 → 4) is a restart, not an outage.
* Every decision is observable: ``supervisor.*`` monitor stats, tracer
  events when tracing is on, and a flight-record dump captured at kill
  time with the restart reason annotated (``extra`` block).

The child process is started through ``multiprocessing`` with the
``spawn`` method by default (a fresh interpreter — forking a parent
whose XLA threads hold locks can deadlock the child; override with
``start_method=`` or ``PADDLE_TPU_SUPERVISOR_START``).  Environment
overrides (``child_env``) are applied around the spawn so settings that
must precede ``import jax`` (``XLA_FLAGS``, ``JAX_PLATFORMS``,
``FLAGS_fault_spec``) reach the child; a callable ``child_env`` receives
the attempt index, which is how chaos drills shrink the mesh between
restarts.

The spawn/heartbeat/backoff/crash-budget core is lifecycle-agnostic and
lives in :class:`ProcessSupervisor`; what varies by workload is only the
*liveness policy* (:meth:`ProcessSupervisor._check_liveness`).
:class:`TrainingSupervisor` keeps the original step-deadline policy.
:class:`ServingSupervisor` supervises a serving replica instead: the
child's engines stamp a heartbeat per dispatched batch/decode step (the
same one-None-check ``obs_hook`` pattern), the parent probes readiness
over the replica's ``/healthz``, and a replica whose HTTP plane stops
answering *and* whose dispatch beats went stale is declared hung — an
idle-but-responsive replica is never killed for quiet traffic.  Warm
restarts readmit traffic only once ``/healthz`` turns ready again (the
entrypoint re-warms its buckets before flipping readiness), which the
parent observes as a ``ready`` transition.
"""
from __future__ import annotations

import math
import os
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, NamedTuple, Optional, Sequence, Union

from ..core import flags, obs_hook
from ..utils import monitor

__all__ = ["Heartbeat", "HeartbeatReader", "HeartbeatWriter",
           "ProcessSupervisor", "ServingSupervisor", "StepWatchdog",
           "SupervisorGaveUp", "SupervisorResult", "TrainingSupervisor",
           "current_heartbeat"]


# ---------------------------------------------------------------------------
# Heartbeat transport: one small checksummed record, overwritten in place
# ---------------------------------------------------------------------------

# wall time, step, predicted_step_s, interval_s, checksum(sum of the 4)
_HB_STRUCT = struct.Struct("<5d")


class Heartbeat(NamedTuple):
    time: float                       # wall clock of the beat
    step: int                         # executor run counter (-1 = birth)
    predicted_step_s: Optional[float]  # compile record prediction, if any
    interval_s: float                 # observed gap since previous beat
                                      # (0 = unknown / fresh compile)


class HeartbeatWriter:
    """Child-side stamp: ``beat()`` pwrites one fixed-size record at
    offset 0.  The record carries its own checksum so a reader racing
    the write sees either the old beat or the new one, never a torn
    hybrid.  Cost per beat: one ``struct.pack`` + one ``pwrite`` —
    cheap enough for every step of a hot training loop."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fd = os.open(path, os.O_CREAT | os.O_WRONLY, 0o644)
        self._last: Optional[float] = None

    def beat(self, step: int, predicted: Optional[dict] = None,
             fresh_compile: bool = False) -> None:
        now = time.time()
        # a compile-run's wall is compile time, not step time: mark its
        # interval unknown so the watchdog's window stays a *step*-time
        # distribution (same exclusion the perf observatory applies)
        interval = 0.0
        if self._last is not None and not fresh_compile:
            interval = max(0.0, now - self._last)
        self._last = now
        ps = 0.0
        if predicted:
            ps = float(predicted.get("predicted_step_s") or 0.0)
        vals = (now, float(step), ps, interval)
        os.pwrite(self._fd, _HB_STRUCT.pack(*vals, math.fsum(vals)), 0)

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


class HeartbeatReader:
    """Parent-side probe: ``read()`` returns the newest intact beat or
    None (file absent, not yet written, or a torn record)."""

    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None

    def read(self) -> Optional[Heartbeat]:
        if self._fd is None:
            try:
                self._fd = os.open(self.path, os.O_RDONLY)
            except OSError:
                return None
        try:
            data = os.pread(self._fd, _HB_STRUCT.size, 0)
        except OSError:
            return None
        if len(data) < _HB_STRUCT.size:
            return None
        t, step, ps, interval, csum = _HB_STRUCT.unpack(data)
        # exact equality on purpose: doubles round-trip struct
        # pack/unpack bit-exactly and fsum is deterministic, so any
        # mismatch at all means a torn record (an isclose-style
        # tolerance on an epoch-seconds-dominated sum would accept
        # hybrids of two adjacent beats)
        if math.fsum((t, step, ps, interval)) != csum:
            return None                      # torn write: keep last view
        return Heartbeat(t, int(step), ps or None, interval)

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


def current_heartbeat() -> Optional[HeartbeatWriter]:
    """The writer installed in this (supervised) process, or None.
    Training loops that don't go through the static Executor can stamp
    progress themselves: ``hb = current_heartbeat(); hb and hb.beat(i)``.
    """
    return obs_hook._heartbeat


# ---------------------------------------------------------------------------
# Watchdog policy: how long may a step take before we call it a hang?
# ---------------------------------------------------------------------------

class StepWatchdog:
    """Per-step deadline policy.

    With a prediction (the compile record's ``predicted_step_s`` rides
    every beat): ``deadline = predicted * drift * multiplier`` where
    ``drift = clamp(median(observed) / predicted, 1, drift_cap)`` — a
    model whose real steps run slower than priced (CPU fallback, a
    congested interconnect) widens its own deadline instead of getting
    killed for honest slowness, but never narrows below the prediction.

    Without a prediction: ``deadline = p99(observed) * multiplier`` over
    a rolling window.  Before any observation: ``max_deadline_s``.
    Either way the deadline only *applies* once the current child has
    produced a step beat — until then (imports, restore, compile) the
    supervisor's ``startup_timeout_s`` is the only clock, which is what
    lets :meth:`reset` keep the observed window across restarts without
    a restarted child's recompile being judged at step scale.  The
    result is always clamped to
    ``[min_deadline_s, max_deadline_s]`` (steps on fast chips are
    micro-seconds — an unclamped deadline would kill on any GC pause).
    """

    def __init__(self, multiplier: float = 8.0, min_deadline_s: float = 5.0,
                 max_deadline_s: float = 900.0, drift_cap: float = 4.0,
                 window: int = 128):
        if multiplier <= 0 or min_deadline_s <= 0:
            raise ValueError("watchdog multiplier/min_deadline_s must be "
                             "positive")
        if max_deadline_s < min_deadline_s:
            raise ValueError("watchdog max_deadline_s < min_deadline_s")
        self.multiplier = float(multiplier)
        self.min_deadline_s = float(min_deadline_s)
        self.max_deadline_s = float(max_deadline_s)
        self.drift_cap = float(drift_cap)
        self._intervals: deque = deque(maxlen=int(window))
        self._last_step: Optional[int] = None
        self._predicted: Optional[float] = None

    def observe(self, hb: Optional[Heartbeat]) -> None:
        if hb is None:
            return
        if hb.step != self._last_step:       # dedupe repeated reads
            self._last_step = hb.step
            if hb.interval_s > 0.0:
                self._intervals.append(hb.interval_s)
        if hb.predicted_step_s:
            self._predicted = hb.predicted_step_s

    def _quantile(self, q: float) -> float:
        vals = sorted(self._intervals)
        return vals[min(len(vals) - 1, int(math.ceil(q * len(vals))) - 1)]

    def drift(self) -> float:
        """Observed-vs-predicted slowdown factor, clamped to
        ``[1, drift_cap]``; 1.0 when either side is unknown."""
        if not self._predicted or not self._intervals:
            return 1.0
        return min(self.drift_cap,
                   max(1.0, self._quantile(0.5) / self._predicted))

    def deadline_s(self) -> float:
        if self._predicted:
            d = self._predicted * self.drift() * self.multiplier
        elif self._intervals:
            d = self._quantile(0.99) * self.multiplier
        else:
            d = self.max_deadline_s
        return min(self.max_deadline_s, max(self.min_deadline_s, d))

    def reset(self) -> None:
        """Fresh child: drop the prediction (it recompiles) but keep the
        observed window — the workload, and therefore the step-time
        distribution, survives a restart."""
        self._predicted = None
        self._last_step = None


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------

class SupervisorGaveUp(RuntimeError):
    """Restart budget exhausted: the job is crash-looping (or exceeded
    ``max_restarts``).  ``exit_history`` carries every attempt's exit
    record so the operator sees *what* kept dying, not just that
    something did."""

    def __init__(self, msg: str, exit_history: List[dict]):
        super().__init__(msg)
        self.exit_history = list(exit_history)


@dataclass
class SupervisorResult:
    """Outcome of a supervised run that ended without giving up."""
    clean_exit: bool                  # child returned 0 un-killed
    stopped: bool = False             # supervisor.stop() / SIGTERM ended it
    attempts: int = 0                 # children started
    restarts: int = 0
    hang_kills: int = 0
    exit_history: List[dict] = field(default_factory=list)


def _child_main(entry, args, kwargs, hb_path):
    """Child bootstrap: install the heartbeat writer, stamp a birth
    beat (the watchdog's startup clock anchor), then hand off to the
    training entrypoint.  Runs in a fresh interpreter under ``spawn``,
    so module state (fault arming via ``FLAGS_fault_spec`` env, jax
    device discovery from ``XLA_FLAGS``) initializes from the
    environment the supervisor staged."""
    w = HeartbeatWriter(hb_path)
    obs_hook.set_heartbeat(w)
    w.beat(step=-1)
    entry(*args, **(kwargs or {}))


class _patched_env:
    """Apply env overrides for the duration of a child spawn (spawn
    inherits ``os.environ`` at exec time).  A None value deletes."""

    def __init__(self, overrides: dict):
        self._overrides = dict(overrides)

    def __enter__(self):
        self._saved = {k: os.environ.get(k) for k in self._overrides}
        for k, v in self._overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        return self

    def __exit__(self, *exc_info):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


class ProcessSupervisor:
    """Run ``entry(*args, **kwargs)`` in a supervised child process and
    keep it alive until it exits cleanly, the restart budget runs out,
    or :meth:`stop` is called.

    ``entry`` must be picklable (module-level callable) under the
    chosen start method.  The entrypoint owns resume semantics: on every
    (re)start it should re-detect its environment and restore from its
    durable state — the supervisor guarantees only *that* it runs again,
    with backoff, and that wedged incarnations die.

    Subclasses specialise the *liveness policy* by overriding
    :meth:`_check_liveness` (and per-attempt state via
    :meth:`_attempt_reset`); spawn, kill escalation, backoff, the crash
    budget, exit history and the flight dumps are shared.
    ``stat_ns`` namespaces the monitor counters (``supervisor.*`` for
    training — the original namespace — ``supervisor.serving.*`` for
    replicas).
    """

    stat_ns = "supervisor"

    def __init__(self, entry: Callable, args: Sequence = (), kwargs=None,
                 *, name: str = "job",
                 watchdog: Optional[StepWatchdog] = None,
                 startup_timeout_s: Optional[float] = 300.0,
                 hang_grace_s: float = 10.0,
                 poll_s: float = 0.25,
                 max_restarts: int = 16,
                 backoff_s: float = 1.0, backoff_max_s: float = 60.0,
                 crash_window_s: float = 300.0, crash_budget: int = 3,
                 child_env: Union[dict, Callable[[int], dict], None] = None,
                 start_method: Optional[str] = None,
                 workdir: Optional[str] = None,
                 dump_flight_on_kill: bool = True):
        self.entry = entry
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.name = name
        self.watchdog = watchdog if watchdog is not None else StepWatchdog()
        self.startup_timeout_s = startup_timeout_s
        self.hang_grace_s = float(hang_grace_s)
        self.poll_s = float(poll_s)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.crash_window_s = float(crash_window_s)
        self.crash_budget = int(crash_budget)
        self._child_env = child_env
        self._method = (start_method
                        or os.environ.get("PADDLE_TPU_SUPERVISOR_START")
                        or "spawn")
        self._workdir = workdir
        self._own_workdir: Optional[str] = None
        self.dump_flight_on_kill = dump_flight_on_kill
        self.exit_history: List[dict] = []
        self.last_heartbeat: Optional[Heartbeat] = None
        self._stop = threading.Event()
        self._proc = None

    # -- observability -----------------------------------------------------
    def _stat(self, suffix: str, v=1) -> None:
        monitor.stat_add(f"{self.stat_ns}.{suffix}", v)

    def _emit(self, action: str, **args) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("supervisor", action,
                     args=dict(args, name=self.name))

    # -- knobs -------------------------------------------------------------
    def _env_for(self, attempt: int) -> dict:
        env = self._child_env
        if env is None:
            out = {}
        elif callable(env):
            out = dict(env(attempt) or {})
        else:
            out = dict(env)
        # fleet telemetry staging: when this process spools, children
        # spool too — flags seed from FLAGS_* env at define time, so a
        # spawn child's fresh interpreter picks these up with zero code
        # changes in the entrypoint.  setdefault keeps an explicit
        # child_env override (or a disable via None) authoritative.
        spool = flags.get_flag("obs_spool_dir")
        if spool:
            out.setdefault("FLAGS_obs_spool_dir", spool)
            out.setdefault("FLAGS_obs_role", f"{self.name}-a{attempt}")
        return out

    def _dir(self) -> str:
        if self._workdir is None:
            import tempfile
            self._own_workdir = tempfile.mkdtemp(
                prefix=f"supervisor_{self.name}_")
            self._workdir = self._own_workdir
        return self._workdir

    def stop(self) -> None:
        """Ask the watch loop to end supervision: the child gets a
        SIGTERM (boundary-save semantics), then a grace-bounded wait —
        no restart follows.  Safe from any thread or signal handler."""
        self._stop.set()

    # -- kill path ---------------------------------------------------------
    def _dump_kill_flight(self, reason: str, attempt: int,
                          hb: Optional[Heartbeat], deadline: float) -> None:
        if not self.dump_flight_on_kill:
            return
        from ..observability.flight import dump_flight
        path = os.path.join(self._dir(),
                            f"supervisor_kill_a{attempt}.json")
        try:
            dump_flight(path, reason=f"supervisor.{reason}", extra={
                "supervisor": self.name,
                "restart_reason": reason,
                "attempt": attempt,
                "last_step": None if hb is None else hb.step,
                "last_beat_age_s": (None if hb is None
                                    else time.time() - hb.time),
                "deadline_s": deadline,
                "exit_history": list(self.exit_history),
            })
        except Exception as e:  # noqa: BLE001 - the kill must proceed
            import warnings
            warnings.warn(f"supervisor: kill-time flight dump failed: {e}")

    def _child_dump_paths(self) -> List[str]:
        """Every per-attempt black box the dead children left in the
        workdir (kill-time flight dumps, the children's own crash
        dumps): the give-up record points at all of them so the
        post-mortem needs no directory spelunking."""
        import glob as _glob
        out: List[str] = []
        for pat in ("supervisor_kill_a*.json", "flight_record*.json",
                    "*_flight.json"):
            out.extend(_glob.glob(os.path.join(self._dir(), pat)))
        return sorted(set(out))

    def _dump_giveup_flight(self, attempts: int,
                            recent_failures: int) -> None:
        if not self.dump_flight_on_kill:
            return
        from ..observability.flight import dump_flight
        path = os.path.join(self._dir(), "supervisor_giveup.json")
        hb = self.last_heartbeat
        try:
            dump_flight(path, reason="supervisor.give_up", extra={
                "supervisor": self.name,
                "attempts": attempts,
                "recent_failures": recent_failures,
                "crash_window_s": self.crash_window_s,
                "crash_budget": self.crash_budget,
                "max_restarts": self.max_restarts,
                "exit_history": list(self.exit_history),
                "child_dumps": self._child_dump_paths(),
                # inlined, not just pointed at: the heartbeat file is
                # a binary record that an operator reading one JSON
                # dump should not have to decode
                "last_heartbeat": None if hb is None else {
                    "time": hb.time,
                    "step": hb.step,
                    "predicted_step_s": hb.predicted_step_s,
                    "interval_s": hb.interval_s,
                    "age_s": round(time.time() - hb.time, 3),
                },
            })
        except Exception as e:  # noqa: BLE001 - give-up must proceed
            import warnings
            warnings.warn(
                f"supervisor: give-up flight dump failed: {e}")
        # when the fleet is spooling, a give-up is a fleet incident:
        # collect every process's telemetry (this parent's lane
        # included) next to the give-up dump
        if flags.get_flag("obs_spool_dir"):
            try:
                from ..observability import fleet
                fleet.collect_fleet_bundle(
                    os.path.join(self._dir(), "fleet_bundle"),
                    extra_paths=self._child_dump_paths() + [path],
                    reason=f"supervisor.give_up:{self.name}",
                    extra={"attempts": attempts,
                           "recent_failures": recent_failures})
            except Exception as e:  # noqa: BLE001
                import warnings
                warnings.warn(
                    f"supervisor: fleet bundle collection failed: {e}")

    def _kill(self, proc, reason: str, attempt: int,
              hb: Optional[Heartbeat], deadline: float) -> None:
        """SIGTERM → grace → SIGKILL.  SIGTERM first on purpose: a slow
        (not wedged) child saves at the next step boundary and exits 0;
        a wedged one ignores it and is SIGKILLed."""
        # 'never beat' and 'stopped beating mid-step' are different
        # diagnoses (environment/startup vs collective deadlock) —
        # keep their counters distinct for whoever alerts on them
        self._stat({"hang": "hang_kills",
                    "startup_timeout": "startup_timeouts"}.get(
                        reason, f"{reason}_kills"))
        self._emit("kill", reason=reason, attempt=attempt,
                   step=None if hb is None else hb.step,
                   deadline_s=round(deadline, 3))
        self._dump_kill_flight(reason, attempt, hb, deadline)
        proc.terminate()
        proc.join(self.hang_grace_s)
        if proc.exitcode is None:
            proc.kill()
            proc.join()

    # -- liveness policy (the subclass hook) --------------------------------
    def _attempt_reset(self) -> None:
        """Per-child-start state reset (a restarted child recompiles /
        re-warms from scratch — stale per-attempt judgments must not
        carry over)."""
        self.watchdog.reset()

    def _check_liveness(self, hb: Optional[Heartbeat], seen_step: bool,
                        started: float) -> Optional[str]:
        """One poll's verdict on the running child: a kill reason
        (``"hang"`` / ``"startup_timeout"`` / policy-specific) or None
        while the child is considered live.  Default policy: the
        training step watchdog."""
        if not seen_step:
            # startup phase: THIS child has produced no step beat yet
            # (birth beat is step -1) — it is importing, restoring, or
            # compiling, and the step-scale watchdog deadline does not
            # apply (restarted children recompile from scratch; the
            # retained interval window must not kill them)
            if (self.startup_timeout_s is not None
                    and time.monotonic() - started
                    > self.startup_timeout_s):
                return "startup_timeout"
            return None
        deadline = self.watchdog.deadline_s()
        if time.time() - hb.time > deadline:
            return "hang"
        return None

    # -- main loop ---------------------------------------------------------
    def run(self) -> SupervisorResult:
        import multiprocessing as mp
        ctx = mp.get_context(self._method)
        attempt = 0
        consecutive = 0
        hang_kills = 0
        self._stop.clear()
        # per-run history: a re-run after stop()/give-up starts with a
        # clean crash-budget window (the raised SupervisorGaveUp keeps
        # its own copy of the old history)
        self.exit_history = []
        while True:
            hb_path = os.path.join(self._dir(), f"heartbeat_a{attempt}")
            try:
                os.remove(hb_path)
            except OSError:
                pass
            env = self._env_for(attempt)
            with _patched_env(env):
                proc = ctx.Process(
                    target=_child_main,
                    args=(self.entry, self.args, self.kwargs, hb_path),
                    name=f"supervised-{self.name}-{attempt}")
                proc.start()
            self._proc = proc
            self._stat("starts")
            self._emit("start", attempt=attempt, pid=proc.pid,
                       env={k: str(v) for k, v in env.items()})
            self._attempt_reset()
            reader = HeartbeatReader(hb_path)
            started = time.monotonic()
            kill_reason = None
            hb = None               # last GOOD beat (a torn read must
            seen_step = False       # not erase the last known view)
            while True:
                proc.join(self.poll_s)
                if proc.exitcode is not None:
                    break
                if self._stop.is_set():
                    kill_reason = "stopped"
                    break
                fresh = reader.read()
                if fresh is not None:
                    hb = fresh
                    self.watchdog.observe(fresh)
                    if fresh.step >= 0:
                        seen_step = True
                reason = self._check_liveness(hb, seen_step, started)
                if reason is not None:
                    kill_reason = reason
                    break
            stopped = self._stop.is_set()
            if kill_reason == "stopped":
                self._emit("stop", attempt=attempt)
                proc.terminate()
                proc.join(max(self.hang_grace_s, 30.0))
                if proc.exitcode is None:
                    proc.kill()
                    proc.join()
            elif kill_reason is not None:
                if kill_reason == "hang":
                    hang_kills += 1
                self._kill(proc, kill_reason, attempt, hb,
                           self.watchdog.deadline_s())
            # the child may have beaten between the last poll and its
            # exit — the record's last_step diagnostic must see the
            # freshest beat, not one up to poll_s stale
            final_hb = reader.read()
            if final_hb is not None:
                self.watchdog.observe(final_hb)
                hb = final_hb
            reader.close()
            self.last_heartbeat = hb
            self._proc = None
            code = proc.exitcode
            rec = {
                "attempt": attempt,
                "exit_code": code,
                "reason": (kill_reason if kill_reason is not None
                           else ("clean" if code == 0
                                 else f"crash(exit={code})")),
                # NOTE: per-incarnation counter (the Executor's run
                # count restarts at 1 in every child) — diagnostic
                # context, not comparable across attempts
                "last_step": None if hb is None else hb.step,
                "runtime_s": round(time.monotonic() - started, 3),
                "time": time.time(),
            }
            attempt += 1
            if stopped:
                self.exit_history.append(rec)
                self._stat("stopped")
                return SupervisorResult(
                    clean_exit=(code == 0), stopped=True, attempts=attempt,
                    restarts=attempt - 1, hang_kills=hang_kills,
                    exit_history=self.exit_history)
            if code == 0 and kill_reason is None:
                self._stat("clean_exits")
                self._emit("clean_exit", attempt=attempt - 1)
                return SupervisorResult(
                    clean_exit=True, attempts=attempt,
                    restarts=attempt - 1, hang_kills=hang_kills,
                    exit_history=self.exit_history)
            # a failure (crash, or a kill — even one that boundary-saved
            # and exited 0): record, budget-check, back off, restart
            self.exit_history.append(rec)
            if kill_reason is None:
                self._stat("crashes")
            # backoff resets when the incarnation survived the whole
            # crash window — by then earlier failures have aged out of
            # the budget anyway, and a job inching forward through
            # occasional node deaths must not accumulate the backoff
            # of a true crash loop.  (The heartbeat step counter can't
            # drive this: it is per-incarnation, not a global step.)
            if rec["runtime_s"] >= self.crash_window_s:
                consecutive = 1
            else:
                consecutive += 1
            self._emit("exit", **rec)
            now = time.time()
            recent = [r for r in self.exit_history
                      if now - r["time"] <= self.crash_window_s]
            if attempt - 1 >= self.max_restarts \
                    or len(recent) > self.crash_budget:
                self._stat("gave_up")
                self._emit("give_up", attempts=attempt,
                           recent_failures=len(recent))
                summary = [(r["reason"], r["exit_code"])
                           for r in self.exit_history]
                # the final black box: a crash-loop give-up is the one
                # exit that leaves NO incarnation behind to explain
                # itself (watchdog kills dump per-attempt, but a crash
                # that exhausts the budget has no kill-time dump) —
                # annotate a last flight dump with the full exit
                # history so the post-mortem starts with evidence
                self._dump_giveup_flight(attempt, len(recent))
                raise SupervisorGaveUp(
                    f"supervisor '{self.name}' giving up after "
                    f"{attempt} attempt(s): {len(recent)} failure(s) "
                    f"inside {self.crash_window_s:.0f}s (budget "
                    f"{self.crash_budget}); exit history: {summary}",
                    self.exit_history)
            backoff = min(self.backoff_s * (2 ** (consecutive - 1)),
                          self.backoff_max_s)
            self._stat("restarts")
            self._stat("backoff_total_s", backoff)
            self._emit("restart", attempt=attempt,
                       backoff_s=round(backoff, 3), reason=rec["reason"])
            # interruptible: stop() during backoff ends supervision
            if self._stop.wait(backoff):
                self._stat("stopped")
                return SupervisorResult(
                    clean_exit=False, stopped=True, attempts=attempt,
                    restarts=attempt - 1, hang_kills=hang_kills,
                    exit_history=self.exit_history)


class TrainingSupervisor(ProcessSupervisor):
    """Supervise a *training* entrypoint: liveness is the per-step
    deadline from :class:`StepWatchdog` over the Executor's heartbeats
    (the original PR-12 policy, inherited unchanged from
    :class:`ProcessSupervisor`'s default ``_check_liveness``).  Stats
    stay in the original ``supervisor.*`` namespace."""

    def __init__(self, entry: Callable, args: Sequence = (), kwargs=None,
                 *, name: str = "train", **kw):
        super().__init__(entry, args, kwargs, name=name, **kw)


class ServingSupervisor(ProcessSupervisor):
    """Supervise a *serving replica*: the child runs a serving
    entrypoint (engine + :class:`~paddle_tpu.serving.ServingServer`)
    and stamps a heartbeat per dispatched batch / decode step through
    the same ``obs_hook`` slot training uses.

    Serving liveness differs from training in one fundamental way: an
    idle replica legitimately stops beating (no traffic, no dispatches),
    so stale beats alone must never kill it.  The policy here is
    conjunctive — a replica is declared hung only when its HTTP plane
    has failed ``ready_fail_budget`` consecutive ``/healthz`` probes
    *and* its newest dispatch beat is older than ``hang_deadline_s``.
    A responsive-but-quiet replica survives; a replica whose dispatcher
    wedged mid-batch keeps answering probes only until the server
    thread pool saturates, then fails both clocks and dies.

    Readiness (HTTP 200 from ``/healthz``; 503 during warmup/drain) is
    tracked as :attr:`ready` with transitions counted
    (``supervisor.serving.ready_transitions``) and emitted on the
    tracer — a warm restart is observable as not-ready → re-warm →
    ready.  Until the replica has been ready once (or produced a
    dispatch beat), ``startup_timeout_s`` is the only clock, exactly
    like training's compile window.  Without a ``health_url`` the
    supervisor degrades to crash-restart-only: no probe means no hang
    verdict, because beats alone cannot distinguish wedged from idle.
    """

    stat_ns = "supervisor.serving"

    def __init__(self, entry: Callable, args: Sequence = (), kwargs=None,
                 *, name: str = "serve", health_url: Optional[str] = None,
                 ready_poll_s: float = 0.5, probe_timeout_s: float = 2.0,
                 ready_fail_budget: int = 6, hang_deadline_s: float = 60.0,
                 **kw):
        super().__init__(entry, args, kwargs, name=name, **kw)
        self.health_url = health_url
        self.ready_poll_s = float(ready_poll_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.ready_fail_budget = int(ready_fail_budget)
        self.hang_deadline_s = float(hang_deadline_s)
        self.ready = False
        self._probe_failures = 0
        self._ever_ready = False
        self._last_probe = 0.0

    def _attempt_reset(self) -> None:
        super()._attempt_reset()
        # a fresh incarnation starts un-probed and not ready: its
        # predecessor's probe verdicts must not kill (or vouch for) it
        self._probe_failures = 0
        self._ever_ready = False
        self._last_probe = 0.0
        self._set_ready(False)

    def _set_ready(self, ready: bool) -> None:
        if ready == self.ready:
            return
        self.ready = ready
        self._stat("ready_transitions")
        self._stat("ready_up" if ready else "ready_down")
        self._emit("ready" if ready else "unready")
        if ready:
            self._ever_ready = True

    def _probe(self):
        """One stdlib HTTP GET against ``health_url``.  Returns
        ``(reachable, ready)``: reachable means the HTTP plane answered
        at all (any status), ready means it answered 200."""
        import http.client
        from urllib.parse import urlparse
        u = urlparse(self.health_url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80, timeout=self.probe_timeout_s)
        try:
            conn.request("GET", u.path or "/healthz")
            status = conn.getresponse().status
            return True, status == 200
        except (OSError, http.client.HTTPException):
            return False, False
        finally:
            conn.close()

    def _check_liveness(self, hb: Optional[Heartbeat], seen_step: bool,
                        started: float) -> Optional[str]:
        now = time.monotonic()
        if self.health_url is not None \
                and now - self._last_probe >= self.ready_poll_s:
            self._last_probe = now
            reachable, is_ready = self._probe()
            self._set_ready(is_ready)
            self._probe_failures = 0 if reachable else \
                self._probe_failures + 1
        if not self._ever_ready and not seen_step:
            # startup / warm-restart window: importing, loading the
            # artifact, AOT-warming buckets — only the startup clock
            # applies until readiness (or the first dispatch beat)
            if (self.startup_timeout_s is not None
                    and now - started > self.startup_timeout_s):
                return "startup_timeout"
            return None
        if self.health_url is None:
            return None          # beats alone can't tell wedged from idle
        if self._probe_failures > self.ready_fail_budget \
                and (hb is None
                     or time.time() - hb.time > self.hang_deadline_s):
            return "hang"
        return None
