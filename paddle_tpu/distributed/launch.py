"""Multi-process launch tooling (``python -m paddle_tpu.distributed.launch``
/ the ``paddle-tpu-launch`` console script / ``spawn``).

Reference: python/paddle/distributed/fleet/launch.py (fleetrun),
launch_utils.py (Pod/Trainer env construction, child watch + terminate),
python/paddle/distributed/spawn.py.

TPU-native process model: ONE controller process per host, all local
devices visible to it (jax); the launcher starts one worker per host
entry (``--ips``) or ``--nproc_per_node`` local workers for CPU-backend
testing, wiring the ``jax.distributed.initialize`` bootstrap env
(coordinator address / process count / process id — the
gen_comm_id_helper.cc TCP-rendezvous analog) that
``init_parallel_env`` consumes.  Children are watched; any non-zero exit
terminates the rest (launch_utils.py watch_local_trainers parity).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "spawn", "main"]


def _worker_env(rank: int, nproc: int, coordinator: str, base=None):
    env = dict(base or os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nproc),
        "COORDINATOR_ADDRESS": coordinator,
        # jax-native names too, for user code calling jax.distributed
        # directly
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(nproc),
        "JAX_PROCESS_ID": str(rank),
    })
    return env


def launch(training_script: str, script_args: Optional[List[str]] = None,
           nproc_per_node: int = 1, ips: Optional[str] = None,
           node_rank: int = 0, master_port: int = 6170,
           log_dir: Optional[str] = None,
           timeout: Optional[float] = None) -> int:
    """Start ``nproc_per_node`` LOCAL worker processes of a (possibly
    multi-host) job with the distributed bootstrap env set; watch them,
    and on any failure terminate the rest (reference: launch_utils.py
    TrainerProc watch loop).  Multi-host: run this on every host in
    ``--ips`` with its own ``--node_rank``; global process ids are
    ``node_rank * nproc_per_node + local`` over a world of
    ``len(ips) * nproc_per_node``.  Returns the first non-zero exit
    code, or 0."""
    script_args = script_args or []
    hosts = ips.split(",") if ips else ["127.0.0.1"]
    coordinator = f"{hosts[0]}:{master_port}"
    world = len(hosts) * nproc_per_node
    if not (0 <= node_rank < len(hosts)):
        raise ValueError(f"node_rank {node_rank} out of range for "
                         f"{len(hosts)} hosts")
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    procs: List[subprocess.Popen] = []
    logs = []
    for local in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local
        env = _worker_env(rank, world, coordinator)
        out = (open(os.path.join(log_dir, f"worker.{rank}.log"), "w")
               if log_dir else None)
        if out is not None:
            logs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, training_script, *script_args], env=env,
            stdout=out, stderr=(subprocess.STDOUT if out else None)))
    nproc_per_node = len(procs)

    rc = 0
    deadline = (time.monotonic() + timeout if timeout is not None
                else None)

    def _kill_all(remaining):
        for r in remaining:
            procs[r].terminate()
        for r in remaining:
            try:
                procs[r].wait(timeout=10)
            except subprocess.TimeoutExpired:
                procs[r].kill()
        remaining.clear()

    try:
        alive = set(range(nproc_per_node))
        while alive:
            if deadline is not None and time.monotonic() > deadline:
                rc = rc or 124  # job deadline exceeded (hung rendezvous?)
                _kill_all(alive)
                break
            for rank in list(alive):
                code = procs[rank].poll()
                if code is None:
                    continue
                alive.discard(rank)
                if code != 0:
                    rc = rc or code
                    # one worker died: take the rest down (reference:
                    # terminate_local_procs)
                    _kill_all(alive)
            time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        rc = 130
    finally:
        for f in logs:
            f.close()
    return rc


def _spawn_entry(rank, nprocs, coordinator, func, args):
    # module-level: the 'spawn' mp context pickles the target
    os.environ.update(_worker_env(rank, nprocs, coordinator, base={}))
    func(*args)


def spawn(func, args=(), nprocs: int = 1, join: bool = True, daemon=False,
          **options):
    """paddle.distributed.spawn parity (reference: spawn.py): run ``func``
    in ``nprocs`` processes with the bootstrap env set.  ``func`` must be
    picklable (module-level), as with the reference's spawn."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    port = int(options.get("master_port", 6170))
    coordinator = f"127.0.0.1:{port}"
    _entry = _spawn_entry

    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_entry,
                        args=(rank, nprocs, coordinator, func, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    # watch ALL children (same discipline as launch()): a failed rank
    # terminates the rest instead of a sequential join hanging on a peer
    # blocked in a collective
    rc = 0
    alive = set(range(nprocs))
    while alive:
        for i in list(alive):
            code = procs[i].exitcode
            if code is None:
                continue
            alive.discard(i)
            if code != 0:
                rc = rc or code
                for j in alive:
                    procs[j].terminate()
                for j in alive:
                    procs[j].join()
                alive.clear()
        time.sleep(0.1)
    if rc:
        raise RuntimeError(f"spawned worker failed with exit code {rc}")
    return procs


def main():
    ap = argparse.ArgumentParser(
        prog="paddle-tpu-launch",
        description="fleetrun/launch parity: start distributed workers "
                    "with the jax bootstrap env wired")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--ips", type=str, default=None,
                    help="comma-separated host list; first is coordinator")
    ap.add_argument("--node_rank", type=int, default=0,
                    help="this host's index into --ips")
    ap.add_argument("--master_port", type=int, default=6170)
    ap.add_argument("--log_dir", type=str, default=None)
    ap.add_argument("training_script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = ap.parse_args()
    sys.exit(launch(ns.training_script, ns.script_args,
                    nproc_per_node=ns.nproc_per_node, ips=ns.ips,
                    node_rank=ns.node_rank, master_port=ns.master_port,
                    log_dir=ns.log_dir))


if __name__ == "__main__":
    main()
