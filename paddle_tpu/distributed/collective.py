"""Collective communication API.

Reference analog: python/paddle/distributed/collective.py:294-735 (the
paddle.distributed.all_reduce/... functions emitting c_* ops backed by NCCL,
operators/collective/ — SURVEY §2.1 'Collective op library').

TPU-native mapping (SURVEY §2.3): the c_* op zoo collapses into
``jax.lax`` collectives over named mesh axes.  Two execution contexts:

- **Inside an SPMD region** (``paddle_tpu.distributed.spmd`` /
  ``shard_map``): ops lower to lax.psum / all_gather / ppermute over ICI —
  this is the performance path, fully fused by XLA.
- **Eager (global view)**: a single controller sees the *global* array —
  every "rank" logically holds the same replicated value.  Collectives
  whose result is well-defined under that replication are computed
  mathematically (all_reduce SUM -> n·x, PROD -> x^n, all_gather -> n
  stacked copies, broadcast -> x); collectives whose result is
  *per-rank-divergent* (scatter, reduce_scatter, alltoall, p2p) cannot be
  represented by one global array and raise UnimplementedError pointing
  at the spmd()/shard_map path.

The reference's stream-ordering ops (c_sync_calc_stream, c_wait_compute)
have NO equivalent: XLA schedules communication and compute itself.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec
from ..core.jax_compat import shard_map

from ..core.dispatch import apply, as_array
from ..core.enforce import UnimplementedError
from ..core.tensor import Tensor
from .mesh import DP_AXIS, axis_size, ensure_mesh, get_mesh

_tls = threading.local()


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Parity shim for paddle.distributed.new_group: a Group names a mesh
    axis (the ring_id → axis-name mapping, SURVEY §2.3)."""

    def __init__(self, axis_name: str = DP_AXIS, ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks
        self.id = id

    @property
    def nranks(self):
        from .mesh import axis_size
        return axis_size(self.axis_name)


_default_group = Group(DP_AXIS)


def new_group(ranks=None, backend=None, axis_name: str = DP_AXIS):
    """reference: collective.py:163.  On TPU a group IS a mesh axis."""
    return Group(axis_name, ranks)


def _axis(group) -> str:
    if group is None:
        return DP_AXIS
    if isinstance(group, Group):
        return group.axis_name
    if isinstance(group, str):
        return group
    return DP_AXIS


def in_spmd() -> Optional[str]:
    """Axis names of the innermost spmd() region, or None."""
    return getattr(_tls, "axes", None)


@contextlib.contextmanager
def _spmd_scope(axes):
    prev = getattr(_tls, "axes", None)
    _tls.axes = axes
    try:
        yield
    finally:
        _tls.axes = prev


def spmd(fn=None, *, in_specs=None, out_specs=None, axes=None,
         check_vma=False):
    """Enter per-device SPMD code: a Tensor-level wrapper over
    ``jax.shard_map``.  Inside, the collective API routes to lax
    collectives over the named axes.

    ``in_specs``/``out_specs``: PartitionSpecs (or tuples) per argument.
    """
    mesh = ensure_mesh()
    axes = tuple(axes) if axes is not None else tuple(mesh.axis_names)

    def decorate(f):
        def wrapper(*tensors):
            arrays = [as_array(t) for t in tensors]
            ispecs = in_specs if in_specs is not None else tuple(
                PartitionSpec(*([None] * a.ndim)) for a in arrays)
            ospecs = out_specs

            def per_device(*arrs):
                with _spmd_scope(axes):
                    out = f(*[Tensor(a) for a in arrs])
                return jax.tree.map(
                    lambda t: t.data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda x: isinstance(x, Tensor))

            sm = shard_map(per_device, mesh=mesh, in_specs=ispecs,
                           out_specs=ospecs, check_vma=check_vma)
            out = sm(*arrays)
            return jax.tree.map(Tensor, out)
        return wrapper
    if fn is not None:
        return decorate(fn)
    return decorate


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """reference: collective.py:294 (c_allreduce_* ops)."""
    ax = _axis(group)
    if in_spmd():
        def _ar(a):
            if op == ReduceOp.SUM:
                return jax.lax.psum(a, ax)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(a, ax)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(a, ax)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(a, ax)
            if op == ReduceOp.PROD:
                # exact for zero/negative inputs (no exp/log trick)
                return jnp.prod(jax.lax.all_gather(a, ax), axis=0)
            raise ValueError(op)
        out = apply(_ar, tensor, op_name="all_reduce")
        tensor._rebind(out)
        return tensor
    # eager global view: every rank holds the same replicated value, so
    # the reduction is computed mathematically (n ranks contribute x)
    n = axis_size(ax)
    if n > 1 and op in (ReduceOp.SUM, ReduceOp.PROD):
        out = (apply(lambda a: a * n, tensor, op_name="all_reduce")
               if op == ReduceOp.SUM
               else apply(lambda a: a ** n, tensor, op_name="all_reduce"))
        tensor._rebind(out)
    # MAX/MIN/AVG of n equal values is the value itself
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """reference: collective.py (c_allgather)."""
    ax = _axis(group)
    if in_spmd():
        out = apply(lambda a: jax.lax.all_gather(a, ax, tiled=True),
                    tensor, op_name="all_gather")
        if tensor_list is not None:
            n = axis_size(ax)
            parts = out.split(n, axis=0)
            tensor_list.extend(parts)
        return out
    # eager: n replicated ranks each contribute the same value
    n = axis_size(ax)
    out = apply(lambda a: jnp.concatenate([a] * n, axis=0), tensor,
                op_name="all_gather") if n > 1 else tensor
    if tensor_list is not None:
        # independent per-rank tensors: mutating one entry must not alias
        # the others (or the source), matching a real all_gather
        tensor_list.extend(Tensor(tensor.data) for _ in range(n))
    return out


def all_gather_object(obj_list, obj, group=None):
    obj_list.append(obj)
    return obj_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    """reference: collective.py (c_broadcast).  In SPMD the value from the
    src index along the axis wins."""
    ax = _axis(group)
    if in_spmd():
        def _bc(a):
            # mask-and-psum: O(|a|) bytes on the wire vs all_gather's
            # O(n·|a|) received per member
            mine = jax.lax.axis_index(ax) == src
            return jax.lax.psum(jnp.where(mine, a, jnp.zeros_like(a)), ax)
        out = apply(_bc, tensor, op_name="broadcast")
        tensor._rebind(out)
        return tensor
    # eager: replicated global view — every rank already holds src's value
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Global view cannot express a dst-only result; computed as
    all_reduce (the value every rank would see on gather)."""
    return all_reduce(tensor, op, group)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group)
    if in_spmd():
        def _rs(a):
            return jax.lax.psum_scatter(a, ax, tiled=True)
        out = apply(_rs, tensor, op_name="reduce_scatter")
        tensor._rebind(out)
        return tensor
    raise UnimplementedError(
        "reduce_scatter outside an spmd() region: the per-rank result is "
        "divergent and cannot be represented by one global array — wrap "
        "the code in paddle_tpu.distributed.spmd(...)")


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if in_spmd():
        n = axis_size(ax)
        if tensor.shape[0] % n:
            raise ValueError(
                f"scatter: leading dim {tensor.shape[0]} is not divisible "
                f"by the {ax!r} axis size {n}")

        def _sc(a):
            idx = jax.lax.axis_index(ax)
            chunk = a.shape[0] // n
            return jax.lax.dynamic_slice_in_dim(a, idx * chunk, chunk, 0)
        out = apply(_sc, tensor, op_name="scatter")
        tensor._rebind(out)
        return tensor
    raise UnimplementedError(
        "scatter outside an spmd() region: the per-rank result is "
        "divergent — wrap the code in paddle_tpu.distributed.spmd(...)")


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """reference: alltoall — the Ulysses/sequence-parallel primitive."""
    ax = _axis(group)
    if in_spmd():
        t = (in_tensor_list if isinstance(in_tensor_list, Tensor)
             else paddle_concat(in_tensor_list))
        def _a2a(a):
            from .mesh import axis_size
            n = axis_size(ax)
            parts = a.reshape(n, a.shape[0] // n, *a.shape[1:])
            return jax.lax.all_to_all(parts, ax, 0, 0, tiled=False).reshape(
                a.shape)
        out = apply(_a2a, t, op_name="alltoall")
        if out_tensor_list is not None:
            out_tensor_list.extend(out.split(axis_size(ax), axis=0))
        return out
    raise UnimplementedError(
        "alltoall outside an spmd() region: the per-rank result is "
        "divergent — wrap the code in paddle_tpu.distributed.spmd(...)")


_P2P_MSG = (
    "independent point-to-point {} does not exist under single-controller "
    "SPMD: a matched send/recv pair across a mesh axis IS a collective "
    "permutation.  Use paddle_tpu.distributed.shift(t, offset) for ring "
    "hops or collective_permute(t, perm) for general patterns (the "
    "send_v2/recv_v2 analog used at pipeline stage boundaries).")


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send (reference: operators/collective/send_v2_op.cc)."""
    raise UnimplementedError(_P2P_MSG.format("send"))


def recv(tensor, src=0, group=None, sync_op=True):
    """p2p recv (reference: operators/collective/recv_v2_op.cc)."""
    raise UnimplementedError(_P2P_MSG.format("recv"))


def shift(tensor, offset: int = 1, group=None):
    """Ring shift over the axis via ppermute: every member receives the
    value held by the member ``offset`` positions before it — the
    SPMD-native form of the send_v2/recv_v2 pipeline hop."""
    ax = _axis(group)
    n = axis_size(ax)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return collective_permute(tensor, perm, group)


def collective_permute(tensor, perm, group=None):
    """Explicit ppermute (the TPU-native send_v2/recv_v2 pair for pipeline
    boundaries; reference: operators/collective/send_v2_op.cc)."""
    ax = _axis(group)
    if in_spmd():
        return apply(lambda a: jax.lax.ppermute(a, ax, perm), tensor,
                     op_name="collective_permute")
    raise UnimplementedError(
        "collective_permute outside an spmd() region: the per-rank result "
        "is divergent — wrap the code in paddle_tpu.distributed.spmd(...)")


def barrier(group=None):
    """reference: barrier_op.  XLA programs are bulk-synchronous; eager
    barrier just blocks the host on outstanding work."""
    (jnp.zeros(()) + 0).block_until_ready()


def get_group(id=0):
    return _default_group


def paddle_concat(tensors):
    import paddle_tpu as paddle
    return paddle.concat(tensors, axis=0)


def split_tensor(tensor, num, axis=0):
    return tensor.split(num, axis=axis)
