"""DistributedStrategy — the single distributed-config object.

Reference: framework/distributed_strategy.proto:126-171 + Python façade
fleet/base/distributed_strategy.py.  The reference compiles this config into
program rewrites via meta-optimizers (fleet_base.py:1159-1202); here it
compiles into mesh shape + sharding rules + step-wrapper choices
(SURVEY §5.6 'TPU equivalent: a single DistributedStrategy-like sharding
config')."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AMPConfig:
    """proto: distributed_strategy.proto AMPConfig."""
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = field(default_factory=list)
    custom_black_list: list = field(default_factory=list)
    use_pure_fp16: bool = False
    dtype: str = "bfloat16"


@dataclass
class RecomputeConfig:
    """proto:67-69 — checkpoint tensors for activation recompute."""
    checkpoints: list = field(default_factory=list)
    enable_offload: bool = False


@dataclass
class ShardingConfig:
    """proto:31-35 — ZeRO-style sharding (sharding_optimizer.py:33).

    ``min_shard_numel``: stage-3 shards every param with at least this many
    elements, padding dim 0 to a multiple of the dp degree when needed (the
    reference shards by padded numel, meta_optimizers/sharding/shard.py);
    smaller params stay replicated (the gather traffic would outweigh the
    memory saved)."""
    sharding_degree: int = 8
    stage: int = 2                    # 1: opt-state, 2: +grads, 3: +params
    fuse_broadcast_MB: float = 32.0
    hybrid_dp: bool = False
    min_shard_numel: int = 1024


@dataclass
class PipelineConfig:
    """proto:120-124 — micro-batching (schedule in section_worker.cc)."""
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"       # or 'F-then-B'
    pp_degree: int = 1


@dataclass
class TensorParallelConfig:
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


@dataclass
class GradientMergeConfig:
    """proto:61-64."""
    k_steps: int = 1
    avg: bool = True


@dataclass
class LocalSGDConfig:
    """proto:51-54."""
    k_steps: int = 1
    begin_step: int = 1


@dataclass
class AdaptiveLocalSGDConfig:
    init_k_steps: int = 1
    begin_step: int = 1


@dataclass
class DGCConfig:
    """proto — deep gradient compression."""
    rampup_begin_step: int = 0
    rampup_step: int = 1
    sparsity: list = field(default_factory=lambda: [0.999])


@dataclass
class LambConfig:
    lamb_weight_decay: float = 0.01
    exclude_from_weight_decay: list = field(default_factory=list)


@dataclass
class LarsConfig:
    lars_coeff: float = 0.001
    lars_weight_decay: float = 0.0005
    epsilon: float = 0.0
    exclude_from_weight_decay: list = field(default_factory=list)


@dataclass
class AsyncConfig:
    """proto:106-118 — parameter-server async/GEO knobs (accepted for
    parity; PS capability is mesh-sharded embedding on TPU)."""
    k_steps: int = -1
    max_merge_var_num: int = 1
    send_queue_size: int = 16
    independent_recv_thread: bool = False
    thread_pool_size: int = 1
    send_wait_times: int = 1
    runtime_split_send_recv: bool = False
    launch_barrier: bool = True


@dataclass
class SequenceParallelConfig:
    """Beyond-reference (SURVEY §5.7): ring-attention context parallelism."""
    sp_degree: int = 1
    ring_attention: bool = True


@dataclass
class GradCommConfig:
    """Beyond-reference (ROADMAP item 2): the gradient-communication
    stage — quantized collectives (EQuARX-style block-scaled int8 /
    bf16 wire) with error feedback, bucketed reduction, and
    latency-vs-bandwidth algorithm selection (see
    ``distributed/grad_comm.py``).

    ``dtype``: ``None`` leaves gradient reduction to GSPMD (the default
    fp32 psum the compiler inserts); ``"fp32"``/``"bf16"``/``"int8"``
    switch to the explicit bucketed stage at that wire precision
    (``"fp32"`` is the measured baseline — same math, but wire bytes
    and collective choices become observable as ``comm.*`` stats).
    ``block_size``: int8 block-scaling granularity (one f32 absmax
    scale per block).  ``error_feedback``: carry the per-device
    quantization residual in the donated executor state and add it
    back into the next step's gradient (keeps the loss trajectory at
    parity with fp32 collectives).  ``scatter_threshold_KB``: buckets
    whose quantized payload is at least this large take the
    bandwidth-optimal psum_scatter/all_to_all + all_gather route;
    smaller (latency-bound) buckets take a single fused psum.
    Bucket sizing itself is ``strategy.fuse_grad_size_in_MB``.

    ``overlap``: how aggressively bucket collectives hide behind the
    backward pass (T3-style fine-grained overlap) — ``"auto"`` picks
    per backend (grad_comm.resolve_overlap_path: fused async
    collectives under the latency-hiding scheduler on TPU/GPU with
    ``FLAGS_xla_latency_hiding``, the explicit ppermute-chunked ring
    on TPU/GPU without it, the fused form on CPU where nothing
    overlaps anyway), ``"ring"`` forces the chunked ring lowering,
    ``"none"`` barriers the whole comm stage after backward (the
    measured no-overlap baseline: step time = compute + comm instead
    of approaching max(compute, comm)).  Flipping it recompiles (the
    plan fingerprint carries it) and re-zeroes the error-feedback
    residuals."""
    dtype: Optional[str] = None       # None=off | 'fp32' | 'bf16' | 'int8'
    block_size: int = 256
    error_feedback: bool = True
    scatter_threshold_KB: float = 32.0
    overlap: str = "auto"             # 'none' | 'auto' | 'ring'


class DistributedStrategy:
    """fleet.DistributedStrategy parity: bool toggles + nested *_configs.

    Toggles map 1:1 to the reference's proto fields; configs accept dicts
    like the reference's property setters."""

    _CONFIGS = {
        "amp_configs": AMPConfig,
        "recompute_configs": RecomputeConfig,
        "sharding_configs": ShardingConfig,
        "pipeline_configs": PipelineConfig,
        "tensor_parallel_configs": TensorParallelConfig,
        "gradient_merge_configs": GradientMergeConfig,
        "localsgd_configs": LocalSGDConfig,
        "adaptive_localsgd_configs": AdaptiveLocalSGDConfig,
        "dgc_configs": DGCConfig,
        "lamb_configs": LambConfig,
        "lars_configs": LarsConfig,
        "a_sync_configs": AsyncConfig,
        "sequence_parallel_configs": SequenceParallelConfig,
        # knob object, not a bool toggle: `strategy.grad_comm.dtype =
        # "int8"` (or a dict assignment) enables the stage
        "grad_comm": GradCommConfig,
    }

    def __init__(self):
        # toggles (proto:126-171)
        self.amp = False
        self.recompute = False
        self.sharding = False
        self.pipeline = False
        self.tensor_parallel = False
        self.gradient_merge = False
        self.localsgd = False
        self.adaptive_localsgd = False
        self.dgc = False
        self.lamb = False
        self.lars = False
        self.a_sync = False
        self.sequence_parallel = False
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True     # XLA does this natively
        # bucket size for the explicit grad_comm reduction stage: small
        # grads fuse into flat buckets of this many MB, each reduced by
        # one collective (the reference Reducer's bucket knob)
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1              # parity no-op
        self.hierarchical_allreduce = False  # topology handled by XLA
        self.elastic = False
        self.auto = False
        # ordered (regex, PartitionSpec) partition rules for the GSPMD
        # sharding engine (distributed/sharding.py); None = default
        # policy (placements + ZeRO-3 dim-0 sharding, else replicated)
        self.sharding_rules = None
        for name, cls in self._CONFIGS.items():
            object.__setattr__(self, "_" + name, cls())

    def __getattr__(self, name):
        if name in DistributedStrategy._CONFIGS:
            return getattr(self, "_" + name)
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in self._CONFIGS:
            cfg = self._CONFIGS[name]()
            if isinstance(value, dict):
                for k, v in value.items():
                    if hasattr(cfg, k):
                        setattr(cfg, k, v)
            else:
                cfg = value
            object.__setattr__(self, "_" + name, cfg)
        else:
            object.__setattr__(self, name, value)

    # -- mesh inference ---------------------------------------------------
    def infer_mesh_shape(self, n_devices: int) -> Dict[str, int]:
        """Derive the mesh {axis: size} this strategy implies.

        The model-parallel degrees must divide the device count exactly
        — flooring ``dp`` would silently idle the remainder devices
        (e.g. mp=3 on 8 chips would "work" on 6 and waste 2)."""
        from .mesh import DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS
        shape: Dict[str, int] = {}
        mp = (self.tensor_parallel_configs.tensor_parallel_degree
              if self.tensor_parallel else 1)
        pp = (self.pipeline_configs.pp_degree if self.pipeline else 1)
        sp = (self.sequence_parallel_configs.sp_degree
              if self.sequence_parallel else 1)
        model = mp * pp * sp
        if n_devices % model != 0:
            from ..core.enforce import InvalidArgumentError
            from .grad_comm import format_mesh_axes
            # the shared axis=degree renderer (grad_comm.format_mesh_
            # axes) names WHICH axis carries which degree, same as the
            # incompatibility message — the two paths cannot drift
            axes = format_mesh_axes(
                {MP_AXIS: mp, PP_AXIS: pp, SP_AXIS: sp}) or "none"
            raise InvalidArgumentError(
                f"DistributedStrategy: the model-parallel degrees "
                f"(mesh axes [{axes}], product {model}) do not divide "
                f"the device count "
                f"({n_devices}) — {n_devices % model} device(s) would "
                f"be silently dropped.  Pick degrees whose product "
                f"divides {n_devices}, or run on "
                f"{(n_devices // model) * model} devices.")
        dp = max(n_devices // model, 1)
        if pp > 1:
            shape[PP_AXIS] = pp
        shape[DP_AXIS] = dp
        if sp > 1:
            shape[SP_AXIS] = sp
        if mp > 1:
            shape[MP_AXIS] = mp
        return shape

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"


def validate_toggles(strategy: "DistributedStrategy",
                     n_devices: Optional[int] = None) -> None:
    """Raise loudly on toggles this build deliberately re-architects away
    (VERDICT r3: silent no-op toggles are worse than missing).  Called by
    both fleet.distributed_optimizer and the step constructors.  Pass
    ``n_devices`` to also reject parallel degrees that do not divide the
    device count (the check :meth:`infer_mesh_shape` enforces)."""
    if n_devices is not None:
        strategy.infer_mesh_shape(int(n_devices))  # raises on non-divisible
    from ..core.enforce import InvalidArgumentError
    fuse = strategy.fuse_grad_size_in_MB
    if not isinstance(fuse, (int, float)) or fuse <= 0:
        raise InvalidArgumentError(
            f"strategy.fuse_grad_size_in_MB={fuse!r}: the gradient "
            f"bucket size must be a positive number of megabytes — each "
            f"bucket is one fused collective, so 0 or negative would "
            f"mean no reduction at all.  Typical values: 8-64 (small = "
            f"more overlap opportunities, large = fewer collectives).")
    gc = strategy.grad_comm
    if gc.dtype not in (None, "fp32", "bf16", "int8"):
        raise InvalidArgumentError(
            f"strategy.grad_comm.dtype={gc.dtype!r}: wire dtype must be "
            f"None (off), 'fp32', 'bf16' or 'int8'.")
    if int(gc.block_size) <= 0:
        raise InvalidArgumentError(
            f"strategy.grad_comm.block_size={gc.block_size!r}: int8 "
            f"block-scaling needs a positive block size (one f32 absmax "
            f"scale per block; typical: 128-1024).")
    if float(gc.scatter_threshold_KB) < 0:
        raise InvalidArgumentError(
            f"strategy.grad_comm.scatter_threshold_KB="
            f"{gc.scatter_threshold_KB!r} must be >= 0 (buckets at least "
            f"this large take psum_scatter+all_gather; smaller take one "
            f"fused psum).")
    from .grad_comm import OVERLAP_MODES
    if gc.overlap not in OVERLAP_MODES:
        raise InvalidArgumentError(
            f"strategy.grad_comm.overlap={gc.overlap!r}: must be 'none' "
            f"(comm strictly after backward — the measured no-overlap "
            f"baseline), 'auto' (per-backend: async collectives under "
            f"the latency-hiding scheduler, chunked ring when the "
            f"compiler won't schedule them, fused on CPU) or 'ring' "
            f"(force the ppermute-chunked ring lowering).")
    if strategy.fp16_allreduce and gc.dtype not in (None, "bf16"):
        raise InvalidArgumentError(
            f"strategy.fp16_allreduce is an alias for grad_comm.dtype="
            f"'bf16' but grad_comm.dtype={gc.dtype!r} is also set — "
            f"drop the alias or the explicit dtype; they conflict.")
    if gc.dtype is not None or strategy.fp16_allreduce:
        still_bad = [name for name, on in
                     (("pipeline", strategy.pipeline),
                      ("sequence_parallel", strategy.sequence_parallel))
                     if on]
        if still_bad:
            raise NotImplementedError(
                f"strategy.grad_comm + strategy."
                f"{' + strategy.'.join(still_bad)}: the explicit "
                f"grad-comm stage composes data parallelism with "
                f"tensor parallelism (mp-sharded params) and ZeRO-3 "
                f"(strategy.sharding stage 3, dp-sharded params), but "
                f"pipeline/sequence-parallel axes schedule cross-stage "
                f"collectives the in-graph shard_map stage cannot "
                f"carry.  Disable grad_comm (leave its dtype None) on "
                f"pp/sp meshes — GSPMD then schedules the grad "
                f"reduction — or drop the pp/sp degrees.")
    if strategy.dgc:
        raise NotImplementedError(
            "strategy.dgc: deep gradient compression (dgc_optimizer.py, "
            "dgc_momentum_op.cc) is a bandwidth-bound-GPU-interconnect "
            "technique; the quantized gradient-collective stage "
            "(strategy.grad_comm.dtype='int8', block-scaled with error "
            "feedback — or the bf16 alias strategy.fp16_allreduce) covers "
            "the wire-compression capability, and top-k sparsified "
            "allreduce is data-dependent (dynamic shapes) which XLA "
            "cannot compile efficiently.")
    if strategy.a_sync:
        raise NotImplementedError(
            "strategy.a_sync: async/GEO parameter-server push-pull "
            "(distributed_strategy.proto:106-118) has no TPU analog — the "
            "PS capability is re-architected as mesh-sharded embedding "
            "tables (paddle_tpu.parallel.ShardedEmbedding), which are "
            "synchronous by construction.  Use strategy.localsgd for "
            "reduced-frequency synchronisation.")
    sm = strategy.pipeline_configs.schedule_mode
    if sm not in ("1F1B", "F-then-B"):
        raise ValueError(
            f"pipeline_configs.schedule_mode must be '1F1B' or "
            f"'F-then-B' (section_worker.cc:115-127), got {sm!r}")
    if strategy.pipeline and sm == "F-then-B":
        raise NotImplementedError(
            "pipeline_configs.schedule_mode='F-then-B': the scan-based "
            "pipeline (parallel/pipeline.py:17-29) differentiates one "
            "fill-drain scan, which collapses the F-then-B/1F1B "
            "distinction — the backward schedule is derived by autodiff "
            "and in-flight state is O(microbatch) either way.  There is "
            "no separate all-forwards-then-all-backwards executor to "
            "select, so this knob cannot take effect; keep the default "
            "'1F1B' (semantically what the compiled schedule delivers).")
