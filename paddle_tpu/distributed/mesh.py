"""Device-mesh management.

TPU-native replacement for the reference's communicator plumbing: where the
reference keys NCCL communicators by ``ring_id`` (reference:
platform/collective_helper.h:52-115) bootstrapped over TCP
(gen_comm_id_helper.cc:126-321), a TPU job has ONE ``jax.sharding.Mesh``
whose *named axes* play the role of rings: 'dp' (data), 'mp' (tensor/model),
'pp' (pipeline), 'sp' (sequence/context).  Multi-host bootstrap is
``jax.distributed.initialize`` (SURVEY §2.3 mapping).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_lock = threading.Lock()
_global_mesh: Optional[Mesh] = None

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"


def init_mesh(shape: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Create + install the global mesh.

    ``shape`` maps axis name -> size, e.g. ``{"dp": 2, "mp": 4}``.  Defaults
    to all devices on a single 'dp' axis (pure data parallel)."""
    global _global_mesh
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = {DP_AXIS: len(devices)}
    sizes = list(shape.values())
    n = int(np.prod(sizes))
    assert n <= len(devices), (
        f"mesh needs {n} devices, only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(sizes)
    mesh = Mesh(arr, tuple(shape.keys()))
    with _lock:
        _global_mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh


def set_mesh(mesh: Mesh):
    global _global_mesh
    with _lock:
        _global_mesh = mesh


def ensure_mesh() -> Mesh:
    if _global_mesh is None:
        return init_mesh()
    return _global_mesh


def axis_size(name: str) -> int:
    m = get_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]


def sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh with the given PartitionSpec."""
    return NamedSharding(ensure_mesh(), PartitionSpec(*spec))


def replicated() -> NamedSharding:
    return NamedSharding(ensure_mesh(), PartitionSpec())
