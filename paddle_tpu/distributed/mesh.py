"""Device-mesh management.

TPU-native replacement for the reference's communicator plumbing: where the
reference keys NCCL communicators by ``ring_id`` (reference:
platform/collective_helper.h:52-115) bootstrapped over TCP
(gen_comm_id_helper.cc:126-321), a TPU job has ONE ``jax.sharding.Mesh``
whose *named axes* play the role of rings: 'dp' (data), 'mp' (tensor/model),
'pp' (pipeline), 'sp' (sequence/context).  Multi-host bootstrap is
``jax.distributed.initialize`` (SURVEY §2.3 mapping).
"""
from __future__ import annotations

import threading
import warnings
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# reentrant: install paths hold it across the equal-mesh short-circuit
# + replace-guard check + write, and _check_replace reads the user
# table (mesh_users) under the same lock
_lock = threading.RLock()
_global_mesh: Optional[Mesh] = None
# live holders of shardings against the current mesh: id(owner) ->
# (weakref(owner), mesh, note).  Owners are compiled executables /
# sharded states; entries die with their owner (see register_mesh_user).
_mesh_users: Dict[int, tuple] = {}

DP_AXIS = "dp"
MP_AXIS = "mp"
PP_AXIS = "pp"
SP_AXIS = "sp"


def register_mesh_user(owner, mesh: Mesh, note: str = "") -> None:
    """Record that ``owner`` (a compiled program / sharded state) holds
    shardings built against ``mesh``.  Replacing that mesh while the
    owner is alive raises (or warns under
    ``FLAGS_mesh_replace_warn_only``) — stale shardings silently
    misplace every subsequent dispatch."""
    key = id(owner)

    def _drop(_ref, _key=key):
        _mesh_users.pop(_key, None)

    with _lock:
        _mesh_users[key] = (weakref.ref(owner, _drop), mesh, note)


def release_mesh_user(owner) -> None:
    with _lock:
        _mesh_users.pop(id(owner), None)


def mesh_users(mesh: Optional[Mesh] = None) -> List[str]:
    """Notes of live owners holding shardings against ``mesh`` (default:
    any mesh)."""
    out = []
    with _lock:
        for key, (ref, m, note) in list(_mesh_users.items()):
            if ref() is None:
                _mesh_users.pop(key, None)
            elif mesh is None or m is mesh:
                out.append(note or f"owner#{key}")
    return out


def _same_mesh(a: Mesh, b: Mesh) -> bool:
    return (a.axis_names == b.axis_names
            and dict(a.shape) == dict(b.shape)
            and list(a.devices.flat) == list(b.devices.flat))


def _check_replace(new_mesh: Mesh) -> None:
    old = _global_mesh
    if old is None or _same_mesh(old, new_mesh):
        return
    users = mesh_users(old)
    if not users:
        return
    from ..core.enforce import PreconditionNotMetError
    from ..core.flags import get_flag
    msg = (
        f"replacing live mesh {dict(old.shape)} with "
        f"{dict(new_mesh.shape)} while {len(users)} compiled program(s) "
        f"still hold shardings against it: {users[:4]} — their "
        f"executables would silently keep the old placement.  Close the "
        f"Executor / drop the train step first (or set "
        f"FLAGS_mesh_replace_warn_only=1 to proceed at your own risk).")
    if get_flag("mesh_replace_warn_only"):
        warnings.warn(msg)
    else:
        raise PreconditionNotMetError(msg)


def init_mesh(shape: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Create + install the global mesh.

    ``shape`` maps axis name -> size, e.g. ``{"dp": 2, "mp": 4}``.  Defaults
    to all devices on a single 'dp' axis (pure data parallel)."""
    global _global_mesh
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = {DP_AXIS: len(devices)}
    sizes = list(shape.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        from ..core.enforce import ResourceExhaustedError, enforce
        enforce(False, (
            f"mesh shape {dict(shape)} needs {n} devices but only "
            f"{len(devices)} are available — shrink an axis (product of "
            f"sizes must be <= device count), or raise the virtual "
            f"device count on CPU via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"),
            exc=ResourceExhaustedError)
    arr = np.asarray(devices[:n]).reshape(sizes)
    mesh = Mesh(arr, tuple(shape.keys()))
    with _lock:
        old = _global_mesh
        if old is not None and _same_mesh(old, mesh):
            # keep the installed object: registered users (and plan
            # caches keyed on mesh identity) stay bound to the live
            # mesh — an equal-but-new object would silently disarm the
            # replace guard
            return old
        _check_replace(mesh)
        _global_mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh


def set_mesh(mesh: Mesh):
    global _global_mesh
    with _lock:
        if _global_mesh is not None and _same_mesh(_global_mesh, mesh):
            return  # equal re-install: keep the object mesh users hold
        _check_replace(mesh)
        _global_mesh = mesh


def ensure_mesh() -> Mesh:
    if _global_mesh is None:
        return init_mesh()
    return _global_mesh


def axis_size(name: str) -> int:
    m = get_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]


def sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh with the given PartitionSpec.

    Also exported as :func:`named_sharding` — the package-level name
    ``paddle_tpu.distributed.sharding`` now refers to the GSPMD
    subsystem MODULE, which shadows this helper there."""
    return NamedSharding(ensure_mesh(), PartitionSpec(*spec))


named_sharding = sharding


def replicated() -> NamedSharding:
    return NamedSharding(ensure_mesh(), PartitionSpec())
