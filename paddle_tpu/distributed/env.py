"""Process-level distributed environment.

Reference analog: ``paddle.distributed.init_parallel_env`` (parallel.py:57),
RoleMaker env parsing (role_maker.py:528), launch/fleetrun.  On TPU the
process model is jax's: one controller process per host, all devices visible;
``jax.distributed.initialize`` is the TCP-bootstrap equivalent
(gen_comm_id_helper.cc analog) for multi-host.
"""
from __future__ import annotations

import os

import jax

from .mesh import ensure_mesh, init_mesh

_initialized = False


def early_init():
    """Run the jax.distributed TCP rendezvous NOW, before anything
    initialises the XLA backend.  Importing paddle_tpu itself touches
    jax.random, so multi-process entrypoints that import the framework at
    module top must call this first (the launcher's env provides the
    coordinator parameters).  Safe no-op when not under a launcher or
    already initialised."""
    coord = os.environ.get("COORDINATOR_ADDRESS") or os.environ.get(
        "PADDLE_MASTER")
    n_proc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # NB: probe with is_initialized(), NOT jax.process_count() — the
    # latter initialises the backend, which would itself make the
    # rendezvous impossible
    from ..core.jax_compat import distributed_is_initialized
    if coord and n_proc > 1 and not distributed_is_initialized():
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=n_proc,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))


def init_parallel_env(mesh_shape=None):
    """paddle.distributed.init_parallel_env parity.

    Single-host: builds the global mesh over local devices.  Multi-host (env
    ``PADDLE_TRAINERS_NUM``>1 or jax coordinator envs set): calls
    ``jax.distributed.initialize`` first so jax.devices() spans all hosts.
    """
    global _initialized
    from .mesh import get_mesh
    cur = get_mesh()
    if _initialized:
        if mesh_shape is None or (
                cur is not None and dict(cur.shape) == dict(mesh_shape)):
            return ensure_mesh()
        # an explicit, different shape re-derives the mesh (the guard in
        # init_mesh rejects it while compiled programs hold shardings)
        return init_mesh(mesh_shape)
    early_init()
    if cur is not None and (mesh_shape is None
                            or dict(cur.shape) == dict(mesh_shape)):
        # a pre-pinned live mesh (possibly over a custom device subset)
        # that already has the requested shape stays installed AS-IS —
        # init_mesh would rebuild it over the default device prefix and
        # silently move the pin
        mesh = cur
    else:
        mesh = init_mesh(mesh_shape)
    _initialized = True
    return mesh


def get_rank(group=None) -> int:
    """Process rank (reference: paddle.distributed.get_rank)."""
    return jax.process_index()


def get_world_size(group=None) -> int:
    """Number of *processes* (reference: get_world_size).  Note: on TPU a
    process controls many devices; device-level parallelism lives in the
    mesh axes, not in process count."""
    return jax.process_count()


def device_world_size() -> int:
    return len(jax.devices())


def is_initialized() -> bool:
    return _initialized


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
