"""First-class GSPMD sharding: partition rules, layouts, sharded trees.

The reference framework distributes through fleet meta-optimizers that
rewrite ProgramDescs around NCCL rings; on TPU the whole capability
collapses into ONE mechanism — a named-axis ``jax.sharding.Mesh`` plus a
``PartitionSpec`` per tensor, with GSPMD inserting every collective.
This module is the subsystem that owns that mapping:

- **Partition-rule engine** — :func:`match_partition_rules` walks a
  named tree (``state_dict``-style nested dicts, or ``[(name, leaf)]``)
  and assigns each leaf the spec of the first ``(regex, PartitionSpec)``
  rule matching its ``/``-joined name.  Scalar leaves are always
  replicated; a non-scalar leaf no rule matches is a hard ``enforce``
  error carrying the nearest-rule hint (a silent default placement is
  how fleets end up replicating their embedding table).
- **Canonical layouts** — :class:`SpecLayout` is the one table naming
  how each parameter family shards over the dp / fsdp / tp / pp axes
  (the SpecLayout pattern; axes default to this repo's mesh names).
- **Tree helpers** — :func:`shard_tree` / :func:`gather_tree` /
  :func:`with_constraint` move whole named trees on and off a mesh.
- **Plans** — :class:`ShardingPlan` binds (mesh, per-param specs, batch
  axes) for one parameter list; the static Executor lowers its donated
  ``_ExecState`` through ``jit(in_shardings=..., out_shardings=...)``
  built from a plan (see ``static/executor.py``), and the cost model
  prices per-shard memory through :meth:`ShardingPlan.divisor`.
- **Reshardable checkpoints** — :class:`ShardedState` adapts a named
  tree of (possibly sharded) arrays to ``SnapshotStore``'s sharded
  protocol: one payload per unique shard, each digest-verified, and
  restore onto a *different* mesh shape reshards — gather-free when the
  stored layout already matches the target, assemble-then-``device_put``
  when it doesn't.
"""
from __future__ import annotations

import difflib
import re
import warnings
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce
from .mesh import DP_AXIS, MP_AXIS, PP_AXIS, SP_AXIS, ensure_mesh, get_mesh

__all__ = [
    "SpecLayout", "ShardingPlan", "ShardedState", "match_partition_rules",
    "named_tree_flatten", "named_tree_unflatten", "shard_tree",
    "gather_tree", "with_constraint", "spec_divisor", "spec_to_json",
    "spec_from_json", "specs_for_state", "plan_for_params",
]

SEP = "/"


# ---------------------------------------------------------------------------
# named trees
# ---------------------------------------------------------------------------

def _leaf_array(leaf):
    """The array behind a leaf (unwraps Tensor/Parameter) or None when
    the leaf is not array-like."""
    from ..core.tensor import Tensor
    if isinstance(leaf, Tensor):
        return leaf.data
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return leaf
    return None


def named_tree_flatten(tree, sep: str = SEP) -> List[Tuple[str, object]]:
    """Flatten nested dicts / lists / tuples / [(name, leaf)] pairs to
    ``[(name, leaf)]`` with ``sep``-joined path names."""
    out: List[Tuple[str, object]] = []

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
        elif isinstance(node, (list, tuple)) and not _is_pair_list(node):
            for i, v in enumerate(node):
                walk(f"{prefix}{sep}{i}" if prefix else str(i), v)
        else:
            out.append((prefix, node))

    if _is_pair_list(tree):
        for name, leaf in tree:
            walk(str(name), leaf)
    else:
        walk("", tree)
    return out


def _is_pair_list(node) -> bool:
    return (isinstance(node, (list, tuple)) and len(node) > 0
            and all(isinstance(e, tuple) and len(e) == 2
                    and isinstance(e[0], str) for e in node))


def named_tree_unflatten(items: Sequence[Tuple[str, object]],
                         sep: str = SEP) -> dict:
    """Rebuild the nested-dict skeleton from ``[(name, leaf)]``."""
    root: dict = {}
    for name, leaf in items:
        parts = name.split(sep)
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = leaf
    return root


# ---------------------------------------------------------------------------
# partition-rule engine
# ---------------------------------------------------------------------------

def _as_spec(s) -> PartitionSpec:
    if isinstance(s, PartitionSpec):
        return s
    if s is None:
        return PartitionSpec()
    if isinstance(s, (tuple, list)):
        return PartitionSpec(*s)
    return PartitionSpec(s)


def _is_scalar(arr) -> bool:
    shape = tuple(getattr(arr, "shape", ()))
    n = 1
    for d in shape:
        n *= int(d)
    return len(shape) == 0 or n == 1


def _nearest_rule(name: str, rules) -> Optional[str]:
    """The rule pattern most similar to ``name`` (regex metachars
    stripped before comparing) — the hint for an unmatched leaf."""
    if not rules:
        return None
    plain = {p: re.sub(r"[\\^$.|?*+()\[\]{}]", "", p) for p, _ in rules}
    best = max(plain, key=lambda p: difflib.SequenceMatcher(
        None, plain[p], name).ratio())
    return best


def match_partition_rules(rules, tree, sep: str = SEP,
                          strict: bool = True):
    """Assign a ``PartitionSpec`` to every leaf of a named tree.

    ``rules`` is an ORDERED sequence of ``(regex, spec)``; the first
    pattern ``re.search``-matching the leaf's ``sep``-joined name wins.
    Scalar leaves (0-dim or one element) are replicated regardless of
    rules.  A non-scalar leaf with no matching rule raises
    :class:`InvalidArgumentError` naming the leaf and the nearest rule
    (``strict=False`` downgrades to replicated, for exploratory use).

    Returns ``[(name, spec)]`` pairs for a pair-list input, or the
    nested-dict skeleton of specs for a nested input.
    """
    rules = [(p, _as_spec(s)) for p, s in (rules or [])]
    items = named_tree_flatten(tree, sep=sep)
    out: List[Tuple[str, PartitionSpec]] = []
    for name, leaf in items:
        arr = _leaf_array(leaf)
        if arr is not None and _is_scalar(arr):
            out.append((name, PartitionSpec()))
            continue
        for pat, spec in rules:
            if re.search(pat, name) is not None:
                out.append((name, spec))
                break
        else:
            if strict:
                hint = _nearest_rule(name, rules)
                enforce(False, (
                    f"no partition rule matches parameter '{name}' "
                    f"({len(rules)} rule(s) tried)"
                    + (f"; nearest rule: r'{hint}'" if hint else "")
                    + " — add an explicit (regex, PartitionSpec) rule "
                    "for it (use r'.*' -> PartitionSpec() as a final "
                    "catch-all to replicate everything unmatched)"),
                    exc=InvalidArgumentError)
            out.append((name, PartitionSpec()))
    if _is_pair_list(tree):
        return out
    return named_tree_unflatten(out, sep=sep)


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs per parameter family over named axes.

    Axis defaults follow this repo's mesh names (``mesh.py``): data
    parallel 'dp' (which doubles as the fsdp/ZeRO axis), tensor
    parallel 'mp', pipeline 'pp', sequence 'sp'.  Use the methods as
    the right-hand sides of partition rules."""

    data_axis: str = DP_AXIS
    fsdp_axis: str = DP_AXIS
    tp_axis: str = MP_AXIS
    pp_axis: str = PP_AXIS
    sp_axis: str = SP_AXIS

    def replicated(self) -> PartitionSpec:
        return PartitionSpec()

    def embedding(self) -> PartitionSpec:
        """[vocab, hidden] row-sharded over tp (VocabParallelEmbedding)."""
        return PartitionSpec(self.tp_axis, None)

    def column_parallel(self) -> PartitionSpec:
        """[in, out] matmul weight split on the output dim."""
        return PartitionSpec(None, self.tp_axis)

    def row_parallel(self) -> PartitionSpec:
        """[in, out] matmul weight split on the input dim."""
        return PartitionSpec(self.tp_axis, None)

    def fsdp(self) -> PartitionSpec:
        """Dim-0 (ZeRO-3 style) shard over the fsdp axis."""
        return PartitionSpec(self.fsdp_axis)

    def norm(self) -> PartitionSpec:
        return PartitionSpec()

    def activations(self) -> PartitionSpec:
        """Batch-major runtime tensors shard over data."""
        return PartitionSpec(self.data_axis)

    def rules(self) -> List[Tuple[str, PartitionSpec]]:
        """A reasonable transformer default: embeddings vocab-sharded,
        norms/biases replicated, 2-D weights fsdp-sharded on dim 0,
        everything else replicated.  Order matters — first match wins."""
        return [
            (r"embedding", self.embedding()),
            (r"(^|/)(ln|norm|layer_norm|bn)[^/]*", self.norm()),
            (r"\.b_\d+$|(^|/)bias$", PartitionSpec()),
            (r"\.w_\d+$|(^|/)weight$", self.fsdp()),
            (r".*", PartitionSpec()),
        ]


# ---------------------------------------------------------------------------
# spec utilities
# ---------------------------------------------------------------------------

def spec_axes(spec: PartitionSpec) -> List[str]:
    axes: List[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(entry)
        else:
            axes.append(entry)
    return axes


def spec_divisor(spec: PartitionSpec, mesh_shape: Dict[str, int]) -> int:
    """How many ways this spec splits a tensor on the given mesh: the
    product of the sizes of every mesh axis the spec shards over."""
    n = 1
    for a in spec_axes(spec):
        n *= int(mesh_shape.get(a, 1))
    return n


def spec_to_json(spec: PartitionSpec) -> list:
    out = []
    for entry in tuple(spec):
        if isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def spec_from_json(data) -> PartitionSpec:
    entries = []
    for entry in (data or []):
        if isinstance(entry, list):
            entries.append(tuple(entry))
        else:
            entries.append(entry)
    return PartitionSpec(*entries)


def _fit_spec_to_mesh(spec: PartitionSpec, shape, mesh,
                      name: str = "", downgrades=None) -> PartitionSpec:
    """Drop spec axes the mesh doesn't carry, and axes whose assigned
    dim isn't divisible by the axis size — the portability rule that
    lets one rule set run unchanged on mesh sizes {1, 8}.

    ``mesh`` is a live ``jax.sharding.Mesh`` OR a plain
    ``{axis: size}`` dict (the mesh-offline shardcheck path — an
    abstract mesh needs no devices).  Every dropped axis counts a
    ``sharding.spec_downgrades`` monitor stat, so a silently-replicated
    axis is visible in /metrics, not just in a scrollback warning.
    Pass ``downgrades`` (a list) to collect structured
    ``(dim, axis, size, reason)`` records instead of issuing
    ``warnings.warn`` — shardcheck promotes them to Diagnostics."""
    from ..utils import monitor
    mesh_shape = dict(mesh) if isinstance(mesh, dict) else dict(mesh.shape)

    def _note(d, a, size, reason):
        monitor.stat_add("sharding.spec_downgrades")
        if downgrades is not None:
            downgrades.append((d, a, size, reason))
        elif size is not None:  # only the divisibility drop warns (the
            warnings.warn(reason)  # mesh-absent drop is the portability
            # contract working as designed, stat-counted but not noisy)

    entries = []
    changed = False
    for d, entry in enumerate(tuple(spec)):
        axes = ([entry] if isinstance(entry, str)
                else list(entry) if isinstance(entry, (tuple, list))
                else [])
        kept = []
        for a in axes:
            size = mesh_shape.get(a)
            dim = int(shape[d]) if d < len(shape) else 0
            if size is None:
                changed = True
                _note(d, a, None,
                      f"sharding: '{name}' dim {d} spec names mesh axis "
                      f"'{a}' which this mesh does not carry; "
                      f"replicating that dim instead")
                continue
            if size > 1 and dim % size != 0:
                changed = True
                _note(d, a, size,
                      f"sharding: '{name}' dim {d} ({dim}) is not "
                      f"divisible "
                      f"by mesh axis '{a}' (size {size}); replicating "
                      f"that "
                      f"dim instead")
                continue
            kept.append(a)
        if not kept:
            entries.append(None)
        elif len(kept) == 1:
            entries.append(kept[0])
        else:
            entries.append(tuple(kept))
    if len(tuple(spec)) > len(shape):
        entries = entries[:len(shape)]
        changed = True
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries) if changed else spec


def specs_for_state(param_specs, state, param_shapes=None):
    """Optimizer-state specs inheriting from the params they belong to.

    ``param_specs`` is a per-param list of PartitionSpec aligned with
    ``state`` — the Optimizer's functional state, a per-param list of
    ``{slot_name: array}``.  A slot whose shape equals the param's
    stored shape inherits the param's spec (Adam m/v shard exactly like
    their param under ZeRO); anything else (scalar betas, step counts,
    factored moments) is replicated.  Pass ``param_shapes`` (per-param
    shape tuples) to enforce the shape check exactly; without it any
    non-scalar slot inherits."""
    out = []
    for i, (spec, slots) in enumerate(zip(param_specs, state)):
        entry = {}
        p_shape = (tuple(param_shapes[i]) if param_shapes is not None
                   else None)
        for k, v in (slots or {}).items():
            arr = _leaf_array(v)
            inherits = (arr is not None and not _is_scalar(arr)
                        and len(spec_axes(spec)) > 0)
            if inherits and p_shape is not None \
                    and tuple(arr.shape) != p_shape:
                inherits = False
            entry[k] = spec if inherits else PartitionSpec()
        out.append(entry)
    return out


# ---------------------------------------------------------------------------
# tree placement
# ---------------------------------------------------------------------------

def _rewrap(leaf, arr):
    from ..core.tensor import Tensor
    if isinstance(leaf, Tensor):
        t = Tensor(arr, stop_gradient=leaf.stop_gradient, name=leaf.name)
        return t
    return arr


def shard_tree(tree, specs=None, mesh: Optional[Mesh] = None,
               rules=None, sep: str = SEP):
    """``device_put`` every leaf of a named tree onto ``mesh`` with its
    PartitionSpec.  ``specs`` may be a matching tree / pair-list / dict
    of name->spec; or pass ``rules`` to derive specs through
    :func:`match_partition_rules`.  With neither, leaves replicate.
    Tensor leaves come back as Tensors holding sharded arrays."""
    mesh = mesh or ensure_mesh()
    items = named_tree_flatten(tree, sep=sep)
    if rules is not None:
        spec_of = dict(match_partition_rules(
            rules, [(n, l) for n, l in items], sep=sep))
    elif specs is not None:
        if isinstance(specs, dict) and not any(
                isinstance(v, dict) for v in specs.values()):
            spec_of = {n: _as_spec(s) for n, s in specs.items()}
        else:
            spec_of = {n: _as_spec(s)
                       for n, s in named_tree_flatten(specs, sep=sep)}
    else:
        spec_of = {}
    out = []
    for name, leaf in items:
        arr = _leaf_array(leaf)
        if arr is None:
            out.append((name, leaf))
            continue
        spec = spec_of.get(name, PartitionSpec())
        spec = _fit_spec_to_mesh(spec, tuple(arr.shape), mesh, name)
        placed = jax.device_put(arr, NamedSharding(mesh, spec))
        out.append((name, _rewrap(leaf, placed)))
    if _is_pair_list(tree):
        return out
    return named_tree_unflatten(out, sep=sep)


def gather_tree(tree, sep: str = SEP):
    """Materialise every leaf as a full host ``np.ndarray`` (the
    all-gather read side of :func:`shard_tree`)."""
    items = named_tree_flatten(tree, sep=sep)
    out = [(n, np.asarray(_leaf_array(l)) if _leaf_array(l) is not None
            else l) for n, l in items]
    if _is_pair_list(tree):
        return out
    return named_tree_unflatten(out, sep=sep)


def with_constraint(x, *spec, mesh: Optional[Mesh] = None):
    """``lax.with_sharding_constraint`` over the (global) mesh — usable
    inside jit-traced code to pin an activation's layout.  Accepts and
    returns Tensors transparently."""
    from ..core.tensor import Tensor
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    arr = x.data if isinstance(x, Tensor) else x
    sp = spec[0] if len(spec) == 1 and isinstance(
        spec[0], PartitionSpec) else PartitionSpec(*spec)
    out = jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, sp))
    return Tensor(out) if isinstance(x, Tensor) else out


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

class ShardingPlan:
    """(mesh, per-param specs, batch axes) for one ordered param list.

    The static Executor compiles its donated state through
    ``jit(in_shardings=..., out_shardings=...)`` built from a plan; the
    cost model divides tensor bytes through :meth:`divisor` to price a
    program per-chip."""

    __slots__ = ("mesh", "param_names", "param_specs", "batch_axes",
                 "label", "grad_comm", "_fp")

    def __init__(self, mesh: Mesh, param_names: Sequence[str],
                 param_specs: Sequence[PartitionSpec],
                 batch_axes: Sequence[str] = (DP_AXIS,), label: str = "",
                 grad_comm=None):
        self.mesh = mesh
        self.param_names = list(param_names)
        self.param_specs = [_as_spec(s) for s in param_specs]
        self.batch_axes = tuple(a for a in batch_axes
                                if a in mesh.shape)
        self.label = label
        # resolved grad_comm.CommSpec (or None): the explicit quantized/
        # bucketed gradient-collective stage the Executor lowers for
        # this plan — part of the compile identity below
        self.grad_comm = grad_comm
        self._fp = None

    # -- identity ----------------------------------------------------------
    def fingerprint(self) -> tuple:
        """Hashable identity for compile caching: a mesh change or a
        spec change must recompile (and names the cause in the
        attribution record).  A plan is immutable, so the tuple is
        computed once — the Executor folds it into the cache key on
        EVERY run."""
        if self._fp is None:
            self._fp = (tuple(self.mesh.shape.items()),
                        tuple(d.id for d in self.mesh.devices.flat),
                        tuple(str(s) for s in self.param_specs),
                        self.batch_axes,
                        (None if self.grad_comm is None
                         else self.grad_comm.fingerprint()))
        return self._fp

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    # -- shardings ---------------------------------------------------------
    def _ns(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, _as_spec(spec))

    def param_spec(self, i: int) -> PartitionSpec:
        return self.param_specs[i]

    def param_sharding(self, i: int) -> NamedSharding:
        return self._ns(self.param_specs[i])

    def replicated(self) -> NamedSharding:
        return self._ns(PartitionSpec())

    def feed_spec(self, shape) -> PartitionSpec:
        """Batch feeds shard dim 0 over the batch axes when divisible;
        anything else replicates (correct, just not parallel)."""
        if not self.batch_axes or not shape:
            return PartitionSpec()
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.shape[a]
        if int(shape[0]) % n != 0:
            return PartitionSpec()
        entry = (self.batch_axes[0] if len(self.batch_axes) == 1
                 else tuple(self.batch_axes))
        return PartitionSpec(entry)

    def feed_sharding(self, shape) -> NamedSharding:
        return self._ns(self.feed_spec(shape))

    def divisor(self, spec) -> int:
        return spec_divisor(_as_spec(spec), dict(self.mesh.shape))

    def batch_divisor(self) -> int:
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.shape[a]
        return n

    def spec_by_name(self, name: str) -> Optional[PartitionSpec]:
        try:
            return self.param_specs[self.param_names.index(name)]
        except ValueError:
            return None

    def __repr__(self):
        sharded = sum(1 for s in self.param_specs if spec_axes(s))
        return (f"ShardingPlan(mesh={dict(self.mesh.shape)}, "
                f"params={len(self.param_specs)} ({sharded} sharded), "
                f"batch_axes={self.batch_axes})")


def plan_for_params(named_params, strategy=None, mesh: Optional[Mesh] = None,
                    rules=None, label: str = "") -> ShardingPlan:
    """Build a :class:`ShardingPlan` for ``[(name, param)]``.

    Per-param resolution order:

    1. explicit ``placement`` metadata (tensor-parallel layers);
    2. ``rules`` (or ``strategy.sharding_rules``) through the full
       rule engine — unmatched non-scalar names are a hard error;
    3. default policy: replicated, except ZeRO-3
       (``strategy.sharding`` stage >= 3) dim-0 shards params of at
       least ``min_shard_numel`` elements over 'dp' when divisible.

    Specs are then fitted to the mesh (axes the mesh doesn't carry, or
    non-divisible dims, replicate) so one config runs on mesh sizes
    {1, 8} unchanged."""
    from ..parallel.tp_layers import get_placement
    mesh = mesh or ensure_mesh()
    if rules is None and strategy is not None:
        rules = getattr(strategy, "sharding_rules", None)
    names = [n for n, _ in named_params]
    arrays = []
    for _, p in named_params:
        arr = _leaf_array(p)
        arrays.append(arr)

    rule_specs: Dict[str, PartitionSpec] = {}
    if rules is not None:
        unplaced = [(n, p) for n, p in named_params
                    if get_placement(p) is None]
        rule_specs = dict(match_partition_rules(rules, unplaced))

    z3 = (strategy is not None and getattr(strategy, "sharding", False)
          and strategy.sharding_configs.stage >= 3
          and DP_AXIS in mesh.shape)
    min_numel = (strategy.sharding_configs.min_shard_numel
                 if z3 else 0)
    dp = mesh.shape.get(DP_AXIS, 1)

    specs: List[PartitionSpec] = []
    for (name, p), arr in zip(named_params, arrays):
        pl = get_placement(p)
        if pl is not None:
            spec = pl
        elif name in rule_specs:
            spec = rule_specs[name]
        elif (z3 and arr is not None and not _is_scalar(arr)
              and int(np.prod(arr.shape)) >= min_numel
              and int(arr.shape[0]) % dp == 0):
            spec = PartitionSpec(DP_AXIS)
        else:
            spec = PartitionSpec()
        shape = tuple(arr.shape) if arr is not None else ()
        specs.append(_fit_spec_to_mesh(spec, shape, mesh, name))
    from . import grad_comm as _gc
    return ShardingPlan(mesh, names, specs, label=label,
                        grad_comm=_gc.resolve(strategy))


# ---------------------------------------------------------------------------
# reshardable checkpoint state (SnapshotStore sharded protocol)
# ---------------------------------------------------------------------------

def _shard_index_json(index, shape) -> list:
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(int(dim))
        out.append([int(start), int(stop)])
    return out


def _index_key(index, shape) -> tuple:
    return tuple((int(sl.indices(int(d))[0]), int(sl.indices(int(d))[1]))
                 for sl, d in zip(index, shape))


class ShardedState:
    """Named tree of (possibly sharded) arrays as a SnapshotStore
    object with per-shard payloads.

    ``SnapshotStore.save`` calls :meth:`shard_state` — one payload per
    *unique* shard (replicas deduped by index) plus a JSON manifest
    recording global shape/dtype, the PartitionSpec, and the mesh shape
    it was saved under; every payload gets its own sha256 digest in the
    snapshot meta.  ``restore`` calls :meth:`load_shard_state` under
    whatever mesh is then live:

    - layouts agree (same mesh shape, same spec) → **gather-free**: each
      payload is placed directly on its device via
      ``jax.make_array_from_single_device_arrays``;
    - layouts differ (different mesh size, or a spec the new mesh can't
      carry) → shards are assembled into the global array on host, then
      ``jax.device_put`` with the target ``NamedSharding`` reshards.

    Construct over a live ``tree`` (nested dicts / flat dict of arrays
    or Tensors), or with ``getter``/``setter`` callables for state that
    must be snapshotted/applied at save/restore time (the Executor's
    device-resident state).  ``specs`` optionally pins restore
    placement by name; default is the saved spec fitted to the current
    mesh."""

    def __init__(self, tree=None, *, getter: Optional[Callable] = None,
                 setter: Optional[Callable] = None, specs=None,
                 mesh: Optional[Mesh] = None, sep: str = SEP):
        self.tree = tree
        self._getter = getter
        self._setter = setter
        self._specs = specs
        self._mesh = mesh
        self._sep = sep

    # -- save side ---------------------------------------------------------
    def _current_tree(self):
        return self._getter() if self._getter is not None else self.tree

    def shard_state(self):
        """-> (manifest dict, {fname: payload bytes})."""
        from ..framework_io import dumps
        items = named_tree_flatten(self._current_tree(), sep=self._sep)
        manifest = {"version": 1, "sep": self._sep, "leaves": []}
        payloads: Dict[str, bytes] = {}
        for li, (name, leaf) in enumerate(items):
            arr = _leaf_array(leaf)
            if arr is None:
                arr = np.asarray(leaf)
            shape = tuple(int(d) for d in arr.shape)
            entry = {"name": name, "shape": list(shape),
                     "dtype": str(np.dtype(arr.dtype)),
                     "spec": spec_to_json(PartitionSpec()),
                     "mesh": {}, "shards": []}
            shards = []
            if isinstance(arr, jax.Array) and isinstance(
                    getattr(arr, "sharding", None), NamedSharding):
                entry["spec"] = spec_to_json(arr.sharding.spec)
                entry["mesh"] = {str(k): int(v) for k, v in
                                 arr.sharding.mesh.shape.items()}
                seen = set()
                for sh in arr.addressable_shards:
                    key = _index_key(sh.index, shape)
                    if key in seen:
                        continue  # replicas: one payload per unique shard
                    seen.add(key)
                    shards.append((sh.index, np.asarray(sh.data)))
            else:
                full = (slice(None),) * len(shape)
                shards.append((full, np.asarray(arr)))
            for k, (index, data) in enumerate(shards):
                fname = f"{li:04d}_{k:04d}.shard"
                payloads[fname] = dumps({"data": data})
                entry["shards"].append({
                    "file": fname,
                    "index": _shard_index_json(index, shape)})
            manifest["leaves"].append(entry)
        return manifest, payloads

    # -- restore side ------------------------------------------------------
    def _target_spec(self, name, saved_spec, shape, mesh):
        if self._specs is not None:
            sp = (self._specs(name) if callable(self._specs)
                  else self._specs.get(name))
            if sp is not None:
                return _fit_spec_to_mesh(_as_spec(sp), shape, mesh, name)
        return _fit_spec_to_mesh(saved_spec, shape, mesh, name)

    def load_shard_state(self, manifest: dict, payloads: Dict[str, bytes]):
        """Rebuild the tree on the CURRENT mesh and apply it (via
        ``setter`` when given, else replacing ``self.tree``).  Payload
        values may be raw bytes or already-decoded payload dicts (the
        SnapshotStore decodes everything up front so a corrupt payload
        can't part-load).  Returns the rebuilt tree."""
        from ..framework_io import loads
        from ..utils import monitor

        def data_of(fname):
            p = payloads[fname]
            if isinstance(p, (bytes, bytearray)):
                p = loads(bytes(p), source=fname)
            return p["data"]

        sep = manifest.get("sep", self._sep)
        mesh = self._mesh or get_mesh()
        items: List[Tuple[str, object]] = []
        for entry in manifest["leaves"]:
            name = entry["name"]
            shape = tuple(int(d) for d in entry["shape"])
            dtype = np.dtype(entry["dtype"])
            saved_spec = spec_from_json(entry["spec"])
            saved_mesh = {k: int(v) for k, v in entry["mesh"].items()}
            shards = [(tuple(slice(a, b) for a, b in sh["index"]),
                       data_of(sh["file"]))
                      for sh in entry["shards"]]
            if mesh is None:
                items.append((name, _assemble(shape, dtype, shards)))
                continue
            target = self._target_spec(name, saved_spec, shape, mesh)
            sharding = NamedSharding(mesh, target)
            if (saved_mesh == {str(k): int(v)
                               for k, v in mesh.shape.items()}
                    and tuple(target) == tuple(saved_spec)
                    and _gather_free_possible(sharding, shape, shards)):
                arr = _place_gather_free(sharding, shape, dtype, shards)
                monitor.stat_add("sharding.restore.gather_free")
            else:
                arr = jax.device_put(_assemble(shape, dtype, shards),
                                     sharding)
                monitor.stat_add("sharding.restore.resharded")
            items.append((name, arr))
        tree = named_tree_unflatten(items, sep=sep)
        if self._setter is not None:
            self._setter(tree)
        else:
            self.tree = tree
        return tree


def _assemble(shape, dtype, shards) -> np.ndarray:
    if len(shards) == 1 and tuple(shards[0][1].shape) == tuple(shape):
        return np.asarray(shards[0][1], dtype=dtype)
    out = np.empty(shape, dtype)
    for index, data in shards:
        out[index] = data
    return out


def _gather_free_possible(sharding: NamedSharding, shape, shards) -> bool:
    """Every device's required shard must exist among the saved unique
    shards (it does whenever the layouts truly agree)."""
    have = {_index_key(i, shape) for i, _ in shards}
    try:
        index_map = sharding.devices_indices_map(shape)
    except Exception:  # pragma: no cover - jax internals moved
        return False
    return all(_index_key(idx, shape) in have
               for idx in index_map.values())


def _place_gather_free(sharding: NamedSharding, shape, dtype, shards):
    by_key = {_index_key(i, shape): np.asarray(d, dtype=dtype)
              for i, d in shards}
    index_map = sharding.devices_indices_map(shape)
    bufs = [jax.device_put(by_key[_index_key(idx, shape)], dev)
            for dev, idx in index_map.items()]
    return jax.make_array_from_single_device_arrays(
        shape, sharding, bufs)
