"""Host-side data-plane anomaly policy: detect → agree → skip →
quarantine → rollback, with zero manual intervention.

PR 12 (``distributed/supervisor.py``) made *process* faults survivable;
this module closes the same loop for *data* faults — a NaN batch, an
overflowed gradient bucket, a corrupted int8 wire payload — which would
otherwise update the weights silently and ruin the run (the reference
framework ships ``check_nan_inf`` wired into every kernel launch for
exactly this class).  The division of labor:

* **In-graph** (``static/executor.py`` + ``distributed/grad_comm.py``,
  ``FLAGS_anomaly_sentry``): per-bucket finiteness scans + grad-norm
  stats collapse to one scalar anomaly flag, psum'd over the dp axis so
  every replica takes the same branch, and the param/slot/step-counter/
  error-feedback update is applied through a ``jnp.where`` select — a
  flagged step is a **bitwise no-op** with no host round-trip, no
  divergence, and no deadlock.  The graph handles *containment*.
* **Host-side** (this module): :class:`AnomalyPolicy` reads the
  sentry's per-step verdict plus a rolling-median loss-spike detector
  (the net for finite corruption — e.g. a bitflipped payload — that a
  non-finite scan cannot flag) and drives the escalation ladder:

  1. **skip** — the graph already dropped the update; the loop should
     re-deliver the same batch (a transient corruption clears itself);
  2. **quarantine** — the batch kept flagging past
     ``FLAGS_anomaly_skip_budget`` consecutive skips: blame it on the
     blame ledger (mirroring the DataLoader's batch-retry blame — a
     batch that repeatedly poisons the step is a data bug, not noise)
     and advance past it;
  3. **rollback** — anomalies persist across a quarantine (the
     corruption reached carried state, or the whole feed is bad):
     restore the newest intact snapshot through the existing
     :class:`~paddle_tpu.utils.checkpoint.SnapshotStore` path and
     re-seed the data order (:attr:`data_seed` bumps; quarantine marks
     from the poisoned timeline are cleared);
  4. **give up** — ``FLAGS_anomaly_rollback_budget`` rollbacks didn't
     help: raise :class:`AnomalyEscalation`, crashing the incarnation
     so the :class:`~paddle_tpu.distributed.supervisor
     .TrainingSupervisor` restarts it — the process-fault ladder is the
     data-fault ladder's last rung.

Observability: every decision lands in ``anomaly.*`` monitor stats
(``skips`` / ``quarantines`` / ``rollbacks`` / ``loss_spikes`` /
``giveups``, per-bucket ``anomaly.bucket.<i>.nonfinite`` counts,
``grad_comm.nonfinite_blocks``, an ``anomaly.grad_norm`` gauge), as
``anomaly`` tracer events carrying the executor's step correlation id,
and — when a flight recorder is installed — a rollback writes an
annotated flight dump (reason ``anomaly.rollback``, the blame ledger
and restored snapshot in ``extra``) so the decision is auditable
post-mortem.

Known semantics: the executor's host-side lr schedule counts dispatched
runs, so a skipped step advances a wall-clock lr schedule by one run
while Adam's bias-correction step counter (device-side) correctly does
not move.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import obs_hook
from ..core.flags import get_flag
from ..utils import monitor

__all__ = ["AnomalyEscalation", "AnomalyPolicy"]


class AnomalyEscalation(RuntimeError):
    """The anomaly ladder ran out of rungs (skip budget, quarantine and
    rollback budget all exhausted): the incarnation gives up so the
    TrainingSupervisor's restart path takes over.  Carries the
    quarantine blame ``ledger`` and the per-event ``history``."""

    def __init__(self, msg: str, ledger: List[dict],
                 history: List[dict]):
        super().__init__(msg)
        self.ledger = list(ledger)
        self.history = list(history)


class AnomalyPolicy:
    """Escalation ladder over the in-graph sentry's per-step verdict.

    Install with :meth:`install` (the static Executor then calls
    :meth:`on_step` after every sentry-compiled train dispatch), tell
    it which batch is in flight with :meth:`note_batch`, and consult
    :meth:`poll` after each ``exe.run`` for the action the loop should
    take::

        policy = AnomalyPolicy(store=store, objects={"train": ss})
        policy.install()
        while applied < steps:
            xb, yb = loader.fetch_batch(cursor)
            policy.note_batch(cursor)
            out = exe.run(main, feed={"x": xb, "y": yb},
                          fetch_list=[loss])
            act = policy.poll()
            if act == "ok":
                applied += 1; cursor += 1
            elif act == "skip":
                pass                       # re-deliver the same batch
            elif act == "quarantine":
                cursor += 1                # blamed; move past it
            elif act == "rollback":
                applied = cursor = policy.resume_step

    ``store``/``objects`` are the :class:`SnapshotStore` and the
    registered snapshot objects rollback restores (omit both to cap the
    ladder at quarantine); ``on_rollback`` is called with the restored
    snapshot's meta entry (re-seed shuffling, reset iterators).

    ``sync=True`` (default) reads the sentry flag on the step that
    produced it — one host sync per step, the right trade for drills
    and supervised production loops that already fetch the loss.
    ``sync=False`` defers each verdict to the *next* ``on_step``, so
    the async dispatch pipeline never stalls; every action then lands
    one step late (the in-graph skip itself is never delayed — only
    the host escalation is).
    """

    def __init__(self, store=None, objects: Optional[Dict] = None,
                 loss_name: Optional[str] = None,
                 skip_budget: Optional[int] = None,
                 rollback_budget: Optional[int] = None,
                 spike_window: Optional[int] = None,
                 spike_factor: Optional[float] = None,
                 on_rollback: Optional[Callable] = None,
                 sync: bool = True):
        if (store is None) != (objects is None):
            raise ValueError("AnomalyPolicy: pass store AND objects "
                             "(or neither) — rollback needs both")
        self.store = store
        self.objects = dict(objects) if objects else None
        self.loss_name = loss_name
        self.skip_budget = int(skip_budget
                               if skip_budget is not None
                               else get_flag("anomaly_skip_budget"))
        self.rollback_budget = int(
            rollback_budget if rollback_budget is not None
            else get_flag("anomaly_rollback_budget"))
        self.spike_factor = float(
            spike_factor if spike_factor is not None
            else get_flag("anomaly_spike_factor"))
        self.on_rollback = on_rollback
        self.sync = bool(sync)
        window = int(spike_window if spike_window is not None
                     else get_flag("anomaly_spike_window"))
        self._losses: deque = deque(maxlen=max(window, 2))
        # ladder state
        self._consecutive = 0
        self.skips = 0
        self.rollbacks = 0
        self.loss_spikes = 0
        self.ledger: List[dict] = []       # quarantine blame ledger
        self.quarantined: set = set()      # batch ids of THIS timeline
        self.history: List[dict] = []      # every non-ok decision
        self.resume_step: Optional[int] = None
        self.data_seed = 0                 # bumps per rollback
        self._batch = None
        self._last = "ok"
        self._pending = None               # deferred (sync=False) step

    # -- wiring ------------------------------------------------------------
    def install(self) -> "AnomalyPolicy":
        """Make this the process-wide policy the Executor notifies."""
        obs_hook.set_anomaly_policy(self)
        return self

    def uninstall(self) -> None:
        if obs_hook._anomaly is self:
            obs_hook.set_anomaly_policy(None)

    def note_batch(self, batch_id) -> None:
        """Name the batch now in flight — the quarantine blame target."""
        self._batch = batch_id

    def poll(self) -> str:
        """The decision for the step(s) observed since the last poll:
        ``"ok"`` | ``"skip"`` | ``"quarantine"`` | ``"rollback"`` —
        reading it resets the slot to ``"ok"``."""
        out, self._last = self._last, "ok"
        return out

    # -- observation (called by the Executor) ------------------------------
    def on_step(self, exe, program, step: int, sentry_vals,
                fetch_names, fetches) -> None:
        if self.sync:
            self._judge(exe, program, step, sentry_vals, fetch_names,
                        fetches, self._batch)
            return
        # deferred mode: judge the PREVIOUS step (its arrays are ready
        # by now — the next dispatch is already queued), keep this one.
        # The in-flight batch id is captured NOW: by the time the
        # deferred judgment runs, note_batch has already named the
        # NEXT step's batch, and quarantine must blame the one that
        # actually ran
        prev, self._pending = self._pending, (exe, program, step,
                                              sentry_vals, fetch_names,
                                              fetches, self._batch)
        if prev is not None:
            self._judge(*prev)

    def flush(self) -> None:
        """Judge a deferred (``sync=False``) step now — call at loop
        boundaries so the last step's verdict is never lost."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._judge(*prev)

    def _loss_of(self, fetch_names, fetches) -> Optional[float]:
        for name, val in zip(fetch_names, fetches):
            if self.loss_name is not None and name != self.loss_name:
                continue
            arr = np.asarray(val)
            if arr.size == 1:
                return float(arr.reshape(()))
        return None

    def _judge(self, exe, program, step, sentry_vals, fetch_names,
               fetches, batch=None) -> None:
        flag, nf, extra, norm2 = (np.asarray(v) for v in sentry_vals)
        anomalous = bool(flag)
        detail = {"step": int(step)}
        if anomalous:
            nf = nf.reshape(-1)
            for i, c in enumerate(nf):
                if int(c):
                    monitor.stat_add(f"anomaly.bucket.{i}.nonfinite",
                                     int(c))
            if int(extra):
                monitor.stat_add("grad_comm.nonfinite_blocks",
                                 int(extra))
            detail.update(kind="nonfinite",
                          nonfinite=int(nf.sum()) + int(extra))
        else:
            g = float(norm2)
            if np.isfinite(g):
                monitor.stat_set("anomaly.grad_norm", float(np.sqrt(g)))
            loss = self._loss_of(fetch_names, fetches)
            if loss is not None and self.spike_factor > 0:
                med = (float(np.median(self._losses))
                       if self._losses else None)
                if med is not None and np.isfinite(loss) \
                        and abs(loss) > self.spike_factor * max(
                            abs(med), 1e-12):
                    # finite corruption (bitflip-class): the update was
                    # already APPLIED — skip can't undo it, but the
                    # ladder's retry/quarantine/rollback rungs can
                    anomalous = True
                    self.loss_spikes += 1
                    monitor.stat_add("anomaly.loss_spikes")
                    detail.update(kind="loss_spike", loss=loss,
                                  median=med)
                else:
                    self._losses.append(loss)
        if not anomalous:
            self._consecutive = 0
            return
        self._consecutive += 1
        detail["consecutive"] = self._consecutive
        detail["batch"] = batch
        if self._consecutive <= self.skip_budget:
            self._decide("skip", detail)
        elif self._consecutive == self.skip_budget + 1:
            self._quarantine(detail, batch)
        else:
            self._rollback_or_give_up(exe, program, detail)

    # -- ladder rungs ------------------------------------------------------
    def _emit(self, action: str, detail: dict) -> None:
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("anomaly", action, args=detail)

    def _decide(self, action: str, detail: dict) -> None:
        self._last = action
        self.history.append(dict(detail, action=action))
        if action == "skip":
            self.skips += 1
            monitor.stat_add("anomaly.skips")
        self._emit(action, detail)

    def _quarantine(self, detail: dict, batch) -> None:
        entry = {"batch": batch, "step": detail["step"],
                 "skips": self._consecutive - 1}
        self.ledger.append(entry)
        self.quarantined.add(batch)
        monitor.stat_add("anomaly.quarantines")
        self._decide("quarantine", dict(detail, blamed=batch))

    def _rollback_or_give_up(self, exe, program, detail: dict) -> None:
        if self.store is not None \
                and self.rollbacks < self.rollback_budget:
            start_epoch = self.store.restore(self.objects)
            if self.store.last_restored is None:
                # the store has never published a snapshot: restore()
                # was a no-op, and "rolling back" would just replay
                # batches onto the live (possibly poisoned) weights —
                # that is a give-up, not a rollback
                monitor.stat_add("anomaly.giveups")
                self._decide("give_up", detail)
                raise AnomalyEscalation(
                    f"anomaly policy giving up at step "
                    f"{detail['step']}: rollback requested but the "
                    f"snapshot store has no published snapshot to "
                    f"restore", self.ledger, self.history)
            snap = dict(self.store.last_restored or {})
            self.resume_step = int(snap.get("step") or 0)
            self.rollbacks += 1
            self.data_seed += 1           # re-seeded data order
            self.quarantined.clear()      # fresh timeline
            self._consecutive = 0
            self._losses.clear()
            monitor.stat_add("anomaly.rollbacks")
            info = dict(detail, snapshot=snap.get("dir"),
                        resume_step=self.resume_step,
                        epoch=start_epoch, data_seed=self.data_seed)
            self._decide("rollback", info)
            # auditable post-mortem: annotate the flight recorder with
            # the rollback decision + blame ledger (best-effort — the
            # rollback itself must never die on observability)
            try:
                from ..observability.flight import (dump_flight,
                                                    flight_recorder_path)
                if flight_recorder_path() is not None:
                    dump_flight(reason="anomaly.rollback", extra={
                        "anomaly": info, "ledger": self.ledger,
                        "history": self.history[-16:],
                        "skips": self.skips,
                        "rollbacks": self.rollbacks,
                    })
            except Exception:  # noqa: BLE001
                pass
            if self.on_rollback is not None:
                self.on_rollback(snap)
            return
        monitor.stat_add("anomaly.giveups")
        self._decide("give_up", detail)
        raise AnomalyEscalation(
            f"anomaly policy giving up at step {detail['step']}: "
            f"{self._consecutive} consecutive anomalous steps after "
            f"{self.rollbacks} rollback(s) (budget "
            f"{self.rollback_budget}) and {len(self.ledger)} "
            f"quarantined batch(es) — handing off to supervisor "
            f"restart", self.ledger, self.history)

    def result(self) -> dict:
        """Summary for gates/drills: counts + ledger."""
        return {
            "skips": self.skips,
            "quarantines": len(self.ledger),
            "rollbacks": self.rollbacks,
            "loss_spikes": self.loss_spikes,
            "ledger": list(self.ledger),
            "resume_step": self.resume_step,
            "data_seed": self.data_seed,
        }
