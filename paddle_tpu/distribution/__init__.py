"""Probability distributions (reference: python/paddle/distribution.py —
Distribution/Uniform/Normal/Categorical)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, as_array
from ..core.rng import next_key
from ..core.tensor import Tensor


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(as_array(self.log_prob(value))))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_array(low)
        self.high = as_array(high)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(np.shape(self.low))
        u = jax.random.uniform(next_key(), shape)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        return apply(
            lambda v: jnp.where((v >= self.low) & (v < self.high),
                                -jnp.log(self.high - self.low), -jnp.inf),
            value, op_name="uniform_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_array(loc)
        self.scale = as_array(scale)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(np.shape(self.loc))
        z = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        loc, scale = self.loc, self.scale
        return apply(
            lambda v: (-((v - loc) ** 2) / (2 * scale ** 2)
                       - jnp.log(scale) - 0.5 * math.log(2 * math.pi)),
            value, op_name="normal_log_prob")

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_array(logits)

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        out = jax.random.categorical(
            next_key(), self.logits, shape=tuple(shape) +
            tuple(np.shape(self.logits))[:-1])
        return Tensor(out)

    def _probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return apply(lambda v: jnp.take_along_axis(
            jnp.broadcast_to(logp, v.shape[:-0] + logp.shape),
            v[..., None].astype(jnp.int32), axis=-1)[..., 0],
            value, op_name="categorical_log_prob")

    def probs(self, value):
        p = self._probs()
        return apply(lambda v: jnp.take_along_axis(
            jnp.broadcast_to(p, v.shape[:-0] + p.shape),
            v[..., None].astype(jnp.int32), axis=-1)[..., 0],
            value, op_name="categorical_probs")

    def entropy(self):
        p = self._probs()
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(p * logp, axis=-1))

    def kl_divergence(self, other):
        p = self._probs()
        return Tensor(jnp.sum(
            p * (jax.nn.log_softmax(self.logits, axis=-1)
                 - jax.nn.log_softmax(other.logits, axis=-1)), axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.p = as_array(probs)

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(np.shape(self.p))
        return Tensor(jax.random.bernoulli(
            next_key(), self.p, shape).astype(jnp.float32))

    def log_prob(self, value):
        p = self.p
        return apply(lambda v: v * jnp.log(jnp.maximum(p, 1e-12))
                     + (1 - v) * jnp.log(jnp.maximum(1 - p, 1e-12)),
                     value, op_name="bernoulli_log_prob")

    def entropy(self):
        p = self.p
        return Tensor(-(p * jnp.log(jnp.maximum(p, 1e-12))
                        + (1 - p) * jnp.log(jnp.maximum(1 - p, 1e-12))))


def kl_divergence(p: Distribution, q: Distribution):
    return p.kl_divergence(q)
