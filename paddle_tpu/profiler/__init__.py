"""paddle.profiler — tracing and host-op profiling.

Reference: paddle/fluid/platform/profiler.h:127 (RecordEvent),
:210-213 (EnableProfiler/DisableProfiler), python/paddle/profiler/
profiler.py (the 2.x Profiler class), tools/timeline.py:131 (chrome
trace export).

TPU-native design: device-side timing belongs to XLA — ``Profiler``
drives ``jax.profiler`` traces (viewable in TensorBoard/Perfetto, the
timeline.py analog), and :class:`RecordEvent` spans emit
``jax.profiler.TraceAnnotation`` so framework phases appear as named
spans on the host track of the same trace.  Host-side per-op timing for
eager mode hooks the single dispatch point (core/dispatch.apply) — the
analog of the reference's RecordEvent inside Tracer::TraceOp — and
``summary()`` prints the top-k table the reference prints on
DisableProfiler.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from enum import Enum
from typing import Dict, List, Optional, Tuple

import jax

from ..core import obs_hook, profiler_hook

__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
    "export_chrome_tracing", "load_profiler_result", "start_profiler",
    "stop_profiler", "profiler_guard",
]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1   # accepted for parity
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class RecordEvent:
    """Named span (reference: platform/profiler.h:127 RecordEvent).

    Context manager or ``begin()``/``end()`` pair.  Emits a
    jax.profiler.TraceAnnotation (shows on the trace's host track),
    accumulates host time under ``name`` when a Profiler is active, and
    lands on the observability tracer as a nested span (correct parent
    attribution) when tracing is enabled.

    Robustness contract: ``end()`` without a prior ``begin()`` is a
    no-op (not a TypeError), ``end()`` is idempotent, and the context
    manager closes the span even when the body raises."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None
        self._span = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        trc = obs_hook._tracer
        if trc is not None:
            self._span = trc.begin_span(self.name)
        self._t0 = time.perf_counter()
        return self

    def end(self):
        t0, self._t0 = self._t0, None
        if t0 is None:      # begin() never ran, or end() ran already
            return
        dt = time.perf_counter() - t0
        ann, self._ann = self._ann, None
        if ann is not None:
            ann.__exit__(None, None, None)
        prof = profiler_hook.current()
        if prof is not None:
            prof._record(self.name, dt, kind="span")
        span, self._span = self._span, None
        if span is not None:
            trc = obs_hook._tracer
            if trc is not None:
                trc.end_span(span)

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """reference: python/paddle/profiler/profiler.py Profiler.

    ``start()``/``stop()`` bracket a profiling session; ``step()`` marks
    iteration boundaries (a RecordEvent span per step).  When
    ``trace_dir`` is set (or ``on_trace_ready=export_chrome_tracing(d)``)
    a jax profiler trace is captured for the session — the device-side
    timeline.  ``summary()`` prints host-side op/span tables.

    Timing semantics: jax dispatch is asynchronous, so by DEFAULT each
    recorded op time covers only the host-side dispatch (Python + trace
    + enqueue) — the device work is still in flight when the timer
    stops.  That is the right view for finding host-bound eager loops,
    but it under-reports device-heavy ops.  Pass ``sync_ops=True`` (or
    set ``FLAGS_profiler_sync_ops``) to block on each op's outputs
    before recording, making the span cover the device work too; this
    serializes the host/device pipeline, so the *sum* becomes accurate
    per-op attribution while the *total* no longer reflects pipelined
    wall-clock.  For true device timelines use ``trace_dir`` (XLA's own
    profiler owns device-side timing)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only: bool = False, trace_dir: Optional[str] = None,
                 sync_ops: Optional[bool] = None):
        from ..core.flags import get_flag
        self.targets = targets
        self._sync_ops = (get_flag("profiler_sync_ops") if sync_ops is None
                          else bool(sync_ops))
        self._on_trace_ready = on_trace_ready
        self._trace_dir = trace_dir or getattr(on_trace_ready, "_dir", None)
        self._timer_only = timer_only
        self._op_stats: Dict[str, List[float]] = defaultdict(
            lambda: [0, 0.0])      # name -> [count, total_s]
        self._span_stats: Dict[str, List[float]] = defaultdict(
            lambda: [0, 0.0])
        self._step_ann = None
        self._step_count = 0
        self._tracing = False

    # -- hook sink ---------------------------------------------------------
    def _record(self, name: str, dt: float, kind: str = "op"):
        table = self._op_stats if kind == "op" else self._span_stats
        ent = table[name]
        ent[0] += 1
        ent[1] += dt

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        profiler_hook.set_active(self)
        if self._trace_dir and not self._timer_only:
            jax.profiler.start_trace(self._trace_dir)
            self._tracing = True
        return self

    def stop(self):
        if self._step_ann is not None:
            self._step_ann.end()
            self._step_ann = None
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
        if profiler_hook.current() is self:  # don't clobber another one
            profiler_hook.set_active(None)
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples: Optional[int] = None):
        if self._step_ann is not None:
            self._step_ann.end()
        self._step_count += 1
        self._step_ann = RecordEvent(
            f"ProfileStep#{self._step_count}").begin()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ---------------------------------------------------------
    def key_averages(self) -> List[Tuple[str, int, float]]:
        """[(op_name, calls, total_ms)] sorted by total host time."""
        rows = [(n, int(c), 1000.0 * t)
                for n, (c, t) in self._op_stats.items()]
        return sorted(rows, key=lambda r: -r[2])

    def summary(self, sorted_by="total", op_detail=True, top_k: int = 20,
                thread_sep=False, time_unit="ms") -> str:
        """Top-k host-time table (the reference's DisableProfiler print,
        platform/profiler.cc PrintProfiler)."""
        lines = []
        if self._span_stats:
            lines.append(f"{'span':<32}{'calls':>8}{'total_ms':>12}"
                         f"{'avg_ms':>10}")
            for n, (c, t) in sorted(self._span_stats.items(),
                                    key=lambda kv: -kv[1][1])[:top_k]:
                lines.append(f"{n:<32}{c:>8}{1000 * t:>12.3f}"
                             f"{1000 * t / max(c, 1):>10.3f}")
            lines.append("")
        lines.append(f"{'op (eager host dispatch)':<32}{'calls':>8}"
                     f"{'total_ms':>12}{'avg_ms':>10}")
        for n, c, tms in self.key_averages()[:top_k]:
            lines.append(f"{n:<32}{c:>8}{tms:>12.3f}"
                         f"{tms / max(c, 1):>10.3f}")
        out = "\n".join(lines)
        print(out)
        return out


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory (reference: profiler.py
    export_chrome_tracing; tools/timeline.py).  The jax trace is already
    chrome/perfetto-compatible — this just points the Profiler at a
    directory."""
    def handler(prof):
        return None

    handler._dir = dir_name
    return handler


def load_profiler_result(path: str):
    """Parity shim: jax traces are read with TensorBoard/Perfetto."""
    raise NotImplementedError(
        "load the trace directory with TensorBoard's profile plugin or "
        "ui.perfetto.dev (jax traces are perfetto-format)")


# -- fluid-era API (reference: python/paddle/fluid/profiler.py) -------------

_legacy: Optional[Profiler] = None


def start_profiler(state="All", tracer_option="Default"):
    global _legacy
    _legacy = Profiler()
    _legacy.start()


def stop_profiler(sorted_key="total", profile_path=None):
    global _legacy
    if _legacy is not None:
        _legacy.stop()
        text = _legacy.summary(sorted_by=sorted_key)
        if profile_path:
            with open(profile_path, "w") as f:
                f.write(text)
        _legacy = None


@contextlib.contextmanager
def profiler_guard(state="All", sorted_key="total", profile_path=None):
    """fluid.profiler.profiler context (reference: fluid/profiler.py:35)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
