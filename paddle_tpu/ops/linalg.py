"""Linear-algebra ops (reference: python/paddle/tensor/linalg.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _norm(a):
        if axis is None and p in ("fro", 2):
            return jnp.sqrt(jnp.sum(jnp.square(a)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax,
                       keepdims=keepdim) ** (1.0 / p)
    return apply(_norm, x, op_name="norm")


def dist(x, y, p=2, name=None):
    def _dist(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply(_dist, x, y, op_name="dist")


def cholesky(x, upper=False, name=None):
    def _chol(a):
        out = jnp.linalg.cholesky(a)
        return jnp.swapaxes(out, -1, -2) if upper else out
    return apply(_chol, x, op_name="cholesky")


def inverse(x, name=None):
    return apply(jnp.linalg.inv, x, op_name="inverse")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                           hermitian=hermitian),
                 x, op_name="pinv")


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), x,
                 op_name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x,
                 op_name="matrix_rank", nondiff=True)


def slogdet(x, name=None):
    return apply(lambda a: tuple(jnp.linalg.slogdet(a)), x,
                 op_name="slogdet")


def det(x, name=None):
    return apply(jnp.linalg.det, x, op_name="det")


def qr(x, mode="reduced", name=None):
    return apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x,
                 op_name="qr")


def svd(x, full_matrices=False, name=None):
    return apply(lambda a: tuple(jnp.linalg.svd(
        a, full_matrices=full_matrices)), x, op_name="svd")


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x,
                 op_name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x,
                 op_name="eigvalsh")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax
    def _tri(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply(_tri, x, y, op_name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply(_lstsq, x, y, op_name="lstsq")


def matmul_transpose(x, y):
    return apply(lambda a, b: jnp.matmul(a, jnp.swapaxes(b, -1, -2)), x, y,
                 op_name="matmul_transpose")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar,
                                   ddof=1 if ddof else 0),
                 x, op_name="cov")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                 op_name="corrcoef")


def histogram(x, bins=100, min=0, max=0, name=None):
    def _hist(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h
    return apply(_hist, x, op_name="histogram", nondiff=True)


def bincount(x, weights=None, minlength=0, name=None):
    def _bc(a):
        return jnp.bincount(a, length=None if minlength == 0 else minlength)
    return apply(_bc, x, op_name="bincount", nondiff=True)
