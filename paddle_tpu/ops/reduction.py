"""Reduction ops (reference: python/paddle/tensor/math.py sum/mean/...,
paddle/fluid/operators/reduce_ops/*)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.dtype import convert_dtype


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.sum(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, op_name="sum")


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x,
                 op_name="mean")


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x,
                 op_name="max")


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x,
                 op_name="min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = convert_dtype(dtype)
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.prod(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, op_name="prod")


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = _norm_axis(axis)
    def _argmax(a):
        out = jnp.argmax(a.reshape(-1) if ax is None else a, axis=0 if ax is None else ax)
        if keepdim and ax is not None:
            out = jnp.expand_dims(out, ax)
        return out.astype(jnp.int32)
    return apply(_argmax, x, op_name="argmax", nondiff=True)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = _norm_axis(axis)
    def _argmin(a):
        out = jnp.argmin(a.reshape(-1) if ax is None else a, axis=0 if ax is None else ax)
        if keepdim and ax is not None:
            out = jnp.expand_dims(out, ax)
        return out.astype(jnp.int32)
    return apply(_argmin, x, op_name="argmin", nondiff=True)


def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x,
                 op_name="all", nondiff=True)


def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x,
                 op_name="any", nondiff=True)


def logsumexp(x, axis=None, keepdim=False, name=None):
    import jax
    ax = _norm_axis(axis)
    return apply(lambda a: jax.scipy.special.logsumexp(a, axis=ax,
                                                       keepdims=keepdim),
                 x, op_name="logsumexp")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, op_name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim),
                 x, op_name="var")


def median(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x,
                 op_name="median")


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), x,
                 op_name="nanmean")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.nansum(a, axis=ax, dtype=d, keepdims=keepdim),
                 x, op_name="nansum")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim),
                 x, op_name="count_nonzero", nondiff=True)
