"""Sequence ops over (padded values, lengths) pairs.

Reference: operators/sequence_ops/*.cc — 19 LoD-tensor kernels.  SURVEY §7
sets the TPU design stance: LoD (ragged) tensors become dense padded
arrays plus a ``lengths`` vector, and every kernel becomes a masked dense
computation with static shapes — jittable, vmappable, MXU-friendly.
Each function documents its reference kernel; semantics over the valid
region match the reference, and the padded region is deterministic
(pad_value or zero), never garbage.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_softmax", "sequence_reverse", "sequence_expand_as",
    "sequence_concat", "sequence_slice", "sequence_erase",
    "sequence_enumerate", "sequence_conv", "sequence_first_step",
    "sequence_last_step",
]

_arr = lambda x: x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _mask(lengths, maxlen, dtype=jnp.bool_):
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """[B] lengths -> [B, maxlen] 0/1 mask (sequence_mask_op.cc)."""
    from ..nn import functional as F
    return F.sequence_mask(lengths, maxlen, dtype)


def sequence_pad(x, lengths, maxlen=None, pad_value=0.0, name=None):
    """Packed [total, ...] rows + [B] lengths -> ([B, maxlen, ...], [B]).

    Reference: sequence_pad_op.cc (LoD -> padded).  ``maxlen`` must be
    static under jit; defaults to the eager max length."""
    xa, la = _arr(x), _arr(lengths).astype(jnp.int32)
    if maxlen is None:
        maxlen = int(jax.device_get(la.max()))

    def fn(xv, lv):
        B = lv.shape[0]
        offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(lv)[:-1]])
        idx = offs[:, None] + jnp.arange(maxlen)[None, :]      # [B, T]
        valid = _mask(lv, maxlen)
        gathered = xv[jnp.clip(idx, 0, xv.shape[0] - 1)]
        shape = (B, maxlen) + (1,) * (xv.ndim - 1)
        return jnp.where(valid.reshape(shape), gathered, pad_value), lv

    return apply(fn, Tensor(xa), Tensor(la), op_name="sequence_pad")


def sequence_unpad(x, lengths, name=None):
    """Padded [B, T, ...] -> packed [sum(lengths), ...]
    (sequence_unpad_op.cc).  The output length is data-dependent, so this
    runs eagerly; under jit use the (values, lengths) pair directly."""
    xa, la = _arr(x), _arr(lengths)
    if isinstance(xa, jax.core.Tracer):
        raise RuntimeError(
            "sequence_unpad produces a data-dependent shape and cannot "
            "run under jit — keep the (padded, lengths) pair (SURVEY §7 "
            "LoD->padding design) or unpad outside the compiled region.")
    import numpy as np
    xn, ln = np.asarray(xa), np.asarray(la)
    rows = [xn[i, :int(l)] for i, l in enumerate(ln)]
    return Tensor(jnp.asarray(np.concatenate(rows, axis=0)))


def _pool_fn(xv, lv, *, pool_type, pad_value):
    T = xv.shape[1]
    m = _mask(lv, T, xv.dtype)
    shape = m.shape + (1,) * (xv.ndim - 2)
    m = m.reshape(shape)
    neg = jnp.asarray(jnp.finfo(xv.dtype).min, xv.dtype)
    cnt = jnp.maximum(lv.astype(xv.dtype), 1.0)
    cnt = cnt.reshape((-1,) + (1,) * (xv.ndim - 2))
    if pool_type == "sum":
        out = (xv * m).sum(axis=1)
    elif pool_type == "average":
        out = (xv * m).sum(axis=1) / cnt
    elif pool_type == "sqrt":
        out = (xv * m).sum(axis=1) / jnp.sqrt(cnt)
    elif pool_type == "max":
        out = jnp.where(m > 0, xv, neg).max(axis=1)
    elif pool_type == "first":
        out = xv[:, 0]
    elif pool_type == "last":
        idx = jnp.maximum(lv - 1, 0)
        out = jnp.take_along_axis(
            xv, idx.reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
        ).squeeze(1)
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    # empty sequences yield pad_value (sequence_pool_op.h)
    empty = (lv == 0).reshape((-1,) + (1,) * (xv.ndim - 2))
    return jnp.where(empty, jnp.asarray(pad_value, xv.dtype), out)


def sequence_pool(x, lengths, pool_type="average", pad_value=0.0,
                  name=None):
    """Masked pooling over time (sequence_pool_op.cc): sum / average /
    sqrt / max / first / last on [B, T, ...] with [B] lengths."""
    return apply(_pool_fn, x, Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_pool", pool_type=pool_type.lower(),
                 pad_value=float(pad_value))


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def _softmax_fn(xv, lv):
    m = _mask(lv, xv.shape[1])
    z = jnp.where(m, xv, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    return jnp.where(m, out, 0.0)


def sequence_softmax(x, lengths, name=None):
    """Per-row masked softmax over the valid prefix
    (sequence_softmax_op.cc)."""
    return apply(_softmax_fn, x, Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_softmax")


def _reverse_fn(xv, lv):
    T = xv.shape[1]
    t = jnp.arange(T)[None, :]
    idx = jnp.where(t < lv[:, None], lv[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        xv, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)), axis=1)


def sequence_reverse(x, lengths=None, name=None):
    """Reverse each row's valid prefix, padding stays in place
    (sequence_reverse_op.cc)."""
    xa = _arr(x)
    if lengths is None:
        lengths = jnp.full((xa.shape[0],), xa.shape[1], jnp.int32)
    return apply(_reverse_fn, Tensor(xa),
                 Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_reverse")


def sequence_expand_as(x, lengths, maxlen, name=None):
    """Tile row i of [B, ...] into [B, maxlen, ...], valid for
    ``lengths[i]`` slots, zero beyond (sequence_expand_as_op.cc under the
    padded design: the reference repeats rows to match a ragged target;
    here the target is (maxlen, lengths))."""
    def fn(xv, lv):
        tiled = jnp.repeat(xv[:, None], maxlen, axis=1)
        m = _mask(lv, maxlen, xv.dtype)
        return tiled * m.reshape(m.shape + (1,) * (xv.ndim - 1))

    return apply(fn, x, Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_expand_as")


def _concat_fn(a, la, b, lb):
    B, Ta = a.shape[:2]
    Tb = b.shape[1]
    T = Ta + Tb
    t = jnp.arange(T)[None, :]                      # [1, T]
    in_a = t < la[:, None]
    ia = jnp.broadcast_to(jnp.clip(t, 0, Ta - 1), (B, T))
    ib = jnp.clip(t - la[:, None], 0, Tb - 1)
    ga = jnp.take_along_axis(
        a, ia.reshape((B, T) + (1,) * (a.ndim - 2)), axis=1)
    gb = jnp.take_along_axis(
        b, ib.reshape((B, T) + (1,) * (b.ndim - 2)), axis=1)
    valid = t < (la + lb)[:, None]
    sel = jnp.where(in_a.reshape((B, T) + (1,) * (a.ndim - 2)), ga, gb)
    return (sel * valid.reshape((B, T) + (1,) * (a.ndim - 2)).astype(
        a.dtype), la + lb)


def sequence_concat(xs, lengths_list, name=None):
    """Concatenate sequences along time per row
    (sequence_concat_op.cc): ([B,Ta,..],[B]) + ([B,Tb,..],[B]) -> ...
    Output time dim = sum of input time dims; valid prefix = sum of
    lengths, padding zeroed."""
    assert len(xs) == len(lengths_list) and len(xs) >= 1
    out = xs[0] if isinstance(xs[0], Tensor) else Tensor(_arr(xs[0]))
    lo = Tensor(_arr(lengths_list[0]).astype(jnp.int32))
    for x2, l2 in zip(xs[1:], lengths_list[1:]):
        out, lo = apply(
            _concat_fn, out, lo, x2, Tensor(_arr(l2).astype(jnp.int32)),
            op_name="sequence_concat")
    return out, lo


def _slice_fn(xv, off, ln):
    B, T = xv.shape[:2]
    t = jnp.arange(T)[None, :]
    idx = jnp.clip(off[:, None] + t, 0, T - 1)
    g = jnp.take_along_axis(
        xv, idx.reshape((B, T) + (1,) * (xv.ndim - 2)), axis=1)
    m = (t < ln[:, None]).reshape((B, T) + (1,) * (xv.ndim - 2))
    return g * m.astype(xv.dtype), ln


def sequence_slice(x, offset, length, name=None):
    """Per-row slice [offset, offset+length) of the time dim, left-packed
    and zero-padded (sequence_slice_op.cc)."""
    return apply(_slice_fn, x, Tensor(_arr(offset).astype(jnp.int32)),
                 Tensor(_arr(length).astype(jnp.int32)),
                 op_name="sequence_slice")


def _erase_fn(ids, lv, *, tokens):
    B, T = ids.shape
    t = jnp.arange(T)[None, :]
    valid = t < lv[:, None]
    erase = jnp.zeros_like(valid)
    for tok in tokens:
        erase = erase | (ids == tok)
    keep = valid & ~erase
    # stable left-compaction: order by (dropped, position)
    rank = jnp.where(keep, t, T + t)
    order = jnp.argsort(rank, axis=1)
    packed = jnp.take_along_axis(ids, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    packed = jnp.where(t < new_len[:, None], packed, 0)
    return packed, new_len


def sequence_erase(x, tokens, lengths=None, name=None):
    """Remove every id in ``tokens``, left-compact, zero-pad; returns
    (ids, new_lengths) (sequence_erase_op.cc)."""
    xa = _arr(x)
    if lengths is None:
        lengths = jnp.full((xa.shape[0],), xa.shape[1], jnp.int32)
    return apply(_erase_fn, Tensor(xa),
                 Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_erase", nondiff=True,
                 tokens=tuple(int(v) for v in tokens))


def _enumerate_fn(ids, lv, *, win_size, pad_value):
    B, T = ids.shape
    t = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]  # [T, W]
    g = ids[:, jnp.clip(t, 0, T - 1)]                            # [B, T, W]
    ok = (t[None] < lv[:, None, None])
    return jnp.where(ok, g, pad_value)


def sequence_enumerate(x, win_size, pad_value=0, lengths=None, name=None):
    """Sliding windows of ids: [B, T] -> [B, T, win_size]
    (sequence_enumerate_op.cc), windows crossing the row's end padded."""
    xa = _arr(x)
    if lengths is None:
        lengths = jnp.full((xa.shape[0],), xa.shape[1], jnp.int32)
    return apply(_enumerate_fn, Tensor(xa),
                 Tensor(_arr(lengths).astype(jnp.int32)),
                 op_name="sequence_enumerate", nondiff=True,
                 win_size=int(win_size), pad_value=int(pad_value))


def _seq_conv_fn(xv, lv, w, *maybe_b, context_length, context_start):
    B, T, D = xv.shape
    m = _mask(lv, T, xv.dtype)[..., None]
    xm = xv * m
    cols = []
    for k in range(context_length):
        shift = context_start + k
        rolled = jnp.roll(xm, -shift, axis=1)
        t = jnp.arange(T)
        ok = ((t + shift >= 0) & (t + shift < T))[None, :, None]
        cols.append(rolled * ok)
    ctx = jnp.concatenate(cols, axis=-1)            # [B, T, ctx*D]
    out = ctx @ w                                   # MXU matmul
    if maybe_b:
        out = out + maybe_b[0]
    return out * m


def sequence_conv(x, lengths, weight, bias=None, context_length=3,
                  context_start=None, name=None):
    """Context-window sequence convolution (sequence_conv_op.cc): gather
    ``context_length`` shifted copies, one [ctx*D, out] matmul — im2col
    over time, phrased as a dense MXU matmul.  ``weight``:
    [context_length * D, out_dim]."""
    if context_start is None:
        context_start = -(context_length // 2)
    args = [x, Tensor(_arr(lengths).astype(jnp.int32)), weight]
    if bias is not None:
        args.append(bias)
    return apply(_seq_conv_fn, *args, op_name="sequence_conv",
                 context_length=int(context_length),
                 context_start=int(context_start))
