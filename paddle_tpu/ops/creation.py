"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, as_array
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor  # re-export to_tensor


def _dt(dtype):
    d = convert_dtype(dtype)
    return get_default_dtype() if d is None else d


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = as_array(fill_value)
    d = convert_dtype(dtype)
    return Tensor(jnp.full(tuple(shape), fill_value, d))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(as_array(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(as_array(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(as_array(x), fill_value,
                                dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    start, end, step = (as_array(v) for v in (start, end, step))
    d = convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(as_array(start), as_array(stop), int(num),
                               dtype=convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(as_array(start), as_array(stop), int(num),
                               base=base, dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset,
                               dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)
    return apply(_diag, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=offset), x, op_name="diagflat")


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=diagonal), x, op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=diagonal), x, op_name="triu")


def meshgrid(*args, **kwargs):
    arrs = [as_array(a) for a in (args[0] if len(args) == 1 and
                                  isinstance(args[0], (list, tuple)) else args)]
    return tuple(Tensor(o) for o in jnp.meshgrid(*arrs, indexing="ij"))


def assign(x, output=None):
    src = jnp.asarray(as_array(x))
    if output is None:
        return Tensor(src)
    output.set_value(src)
    return output


def clone(x, name=None):
    return apply(jnp.copy, x, op_name="clone")


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(as_array(x).shape))))


def complex(real, imag, name=None):
    return apply(lambda r, i: jax.lax.complex(r, i), real, imag,
                 op_name="complex")


import jax  # noqa: E402  (used by complex)
