"""Comparison / logical ops (reference: python/paddle/tensor/logic.py,
operators/controlflow/compare_op.cc, logical_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, as_array
from ..core.tensor import Tensor


def _cmp(jfn, name):
    def op(x, y, name=None):
        return apply(jfn, x, y, op_name=name, nondiff=True)
    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return apply(jnp.logical_not, x, op_name="logical_not", nondiff=True)


def bitwise_not(x, name=None):
    return apply(jnp.bitwise_not, x, op_name="bitwise_not", nondiff=True)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                          equal_nan=equal_nan),
                 x, y, op_name="isclose", nondiff=True)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 x, y, op_name="allclose", nondiff=True)


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), x, y,
                 op_name="equal_all", nondiff=True)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(as_array(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def in1d(x, test, name=None):
    return apply(lambda a, b: jnp.isin(a, b), x, test, op_name="isin",
                 nondiff=True)


isin = in1d
