"""Random ops drawing from the global Generator
(reference: python/paddle/tensor/random.py, operators/uniform_random_op.cc,
gaussian_random_op.cc; generator state in framework/generator.h)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import as_array
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.rng import next_key
from ..core.tensor import Tensor


def _dt(dtype):
    d = convert_dtype(dtype)
    return get_default_dtype() if d is None else d


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), tuple(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), tuple(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, tuple(shape), _dt(dtype),
                                     minval=float(as_array(min)),
                                     maxval=float(as_array(max))))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = ()
        m = as_array(mean)
        if hasattr(m, "shape"):
            shape = m.shape
    out = jax.random.normal(next_key(), tuple(shape), get_default_dtype())
    return Tensor(out * as_array(std) + as_array(mean))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype)
    if d == jnp.int64:
        d = jnp.int32  # x64 disabled by default; int32 is the TPU-native int
    return Tensor(jax.random.randint(next_key(), tuple(shape), low, high,
                                     dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    a = as_array(x)
    return randint(low, high, tuple(a.shape), dtype or "int32")


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(jnp.int32))


def shuffle(x, axis=0, name=None):
    return Tensor(jax.random.permutation(next_key(), as_array(x), axis=axis,
                                         independent=False))


def bernoulli(x, name=None):
    a = as_array(x)
    return Tensor(jax.random.bernoulli(next_key(), a).astype(a.dtype))


def poisson(x, name=None):
    a = as_array(x)
    return Tensor(jax.random.poisson(next_key(), a).astype(a.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    a = as_array(x)
    logits = jnp.log(jnp.maximum(a, 1e-30))
    if replacement:
        # categorical broadcasts batch dims leading: sample with
        # num_samples leading, then move it to the trailing position
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(num_samples, *a.shape[:-1]))
        out = jnp.moveaxis(out, 0, -1) if a.ndim > 1 else out.reshape(-1)
    else:
        # Gumbel top-k trick gives sampling without replacement
        g = jax.random.gumbel(next_key(), a.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int32))


def exponential_(x, lam=1.0, name=None):
    a = as_array(x)
    out = jax.random.exponential(next_key(), a.shape, a.dtype) / lam
    x.set_value(out)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    a = as_array(x)
    x.set_value(jax.random.normal(next_key(), a.shape, a.dtype) * std + mean)
    return x


def uniform_(x, min=-1.0, max=1.0, name=None):
    a = as_array(x)
    x.set_value(jax.random.uniform(next_key(), a.shape, a.dtype,
                                   minval=min, maxval=max))
    return x
