"""Shape / layout / indexing manipulation ops
(reference: python/paddle/tensor/manipulation.py, operators/reshape_op.cc,
transpose_op.cc, concat_op.cc, gather_op.*, scatter_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply, as_array
from ..core.dtype import convert_dtype
from builtins import slice as builtins_slice
from ..core.tensor import Tensor


def cast(x, dtype):
    d = convert_dtype(dtype)
    return apply(lambda a: a.astype(d), x, op_name="cast")


def _reshape_fn(a, *, shape):
    return jnp.reshape(a, shape)


def reshape(x, shape, name=None):
    shape = tuple(int(s) if not hasattr(s, "item") else int(s.item())
                  for s in shape)
    return apply(_reshape_fn, x, op_name="reshape", cacheable=True,
                 shape=shape)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._rebind(out)
    return x


def _transpose_fn(a, *, perm):
    return jnp.transpose(a, perm)


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return apply(_transpose_fn, x, op_name="transpose", cacheable=True,
                 perm=perm)


def t(x, name=None):
    return apply(lambda a: a.T, x, op_name="t")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), x,
                 op_name="moveaxis")


def swapaxes(x, axis1, axis2, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis1, axis2), x,
                 op_name="swapaxes")


def _flatten_fn(a, *, start_axis, stop_axis):
    nd = a.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
    return jnp.reshape(a, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return apply(_flatten_fn, x, op_name="flatten", cacheable=True,
                 start_axis=int(start_axis), stop_axis=int(stop_axis))


def squeeze(x, axis=None, name=None):
    def _squeeze(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply(_squeeze, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    def _unsqueeze(a):
        out = a
        for ax in sorted(int(v) for v in axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply(_unsqueeze, x, op_name="unsqueeze")


def concat(x, axis=0, name=None):
    axis = int(as_array(axis)) if not isinstance(axis, int) else axis
    tensors = list(x)
    return apply(lambda *xs: jnp.concatenate(xs, axis=axis), *tensors,
                 op_name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply(lambda *xs: jnp.stack(xs, axis=axis), *tensors,
                 op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis)
    def _split(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        sections = list(num_or_sections)
        total = a.shape[axis]
        known = [s for s in sections if s != -1]
        if len(known) < len(sections):
            fill = total - int(np.sum(known))
            sections = [fill if s == -1 else s for s in sections]
        idx = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(a, idx, axis=axis))
    return apply(_split, x, op_name="split")


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = as_array(x).shape[axis]
    def _unbind(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return apply(_unbind, x, op_name="unbind")


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = tuple(int(r) for r in repeat_times)
    return apply(lambda a: jnp.tile(a, reps), x, op_name="tile")


def expand(x, shape, name=None):
    shape = tuple(int(s) for s in shape)
    def _expand(a):
        tgt = tuple(a.shape[i - (len(shape) - a.ndim)] if s == -1 else s
                    for i, s in enumerate(shape))
        return jnp.broadcast_to(a, tgt)
    return apply(_expand, x, op_name="expand")


def expand_as(x, y, name=None):
    tgt = tuple(as_array(y).shape)
    return apply(lambda a: jnp.broadcast_to(a, tgt), x, op_name="expand_as")


def broadcast_to(x, shape, name=None):
    return apply(lambda a: jnp.broadcast_to(a, tuple(shape)), x,
                 op_name="broadcast_to")


def broadcast_tensors(inputs, name=None):
    return apply(lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *inputs,
                 op_name="broadcast_tensors")


def flip(x, axis, name=None):
    axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda a: jnp.flip(a, axis=axes), x, op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x,
                 op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), x, op_name="roll")


def repeat_interleave(x, repeats, axis=None, name=None):
    r = as_array(repeats)
    return apply(lambda a: jnp.repeat(a, r, axis=axis), x,
                 op_name="repeat_interleave")


# -- gather / scatter family ----------------------------------------------

def gather(x, index, axis=0, name=None):
    axis = int(as_array(axis)) if not isinstance(axis, int) else axis
    return apply(lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i,
                                       axis=axis),
                 x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def _gather_nd(a, idx):
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return apply(_gather_nd, x, index, op_name="gather_nd")


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                 arr, indices, op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def _put(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        mode = {"add": "add", "mul": "multiply", "multiply": "multiply"}[reduce]
        dims = list(range(a.ndim))
        # scatter via explicit index grid
        idx_grids = jnp.meshgrid(*[jnp.arange(s) for s in i.shape],
                                 indexing="ij")
        full_idx = list(idx_grids)
        full_idx[axis] = i
        if mode == "add":
            return a.at[tuple(full_idx)].add(v)
        return a.at[tuple(full_idx)].multiply(v)
    return apply(_put, arr, indices, values, op_name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    """reference: operators/scatter_op.cc (1-D index into dim 0)."""
    def _scatter(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # paddle overwrite=False: zero target rows then accumulate
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply(_scatter, x, index, updates, op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def _snd(a, i, u):
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(_snd, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    z = Tensor(jnp.zeros(tuple(shape), as_array(updates).dtype))
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply(lambda a, i: jnp.take(a, i, axis=axis), x, index,
                 op_name="index_select")


def index_sample(x, index, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index,
                 op_name="index_sample")


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only op (documented XLA limitation)
    a = np.asarray(as_array(x))
    m = np.asarray(as_array(mask))
    return Tensor(jnp.asarray(a[m]))


def masked_fill(x, mask, value, name=None):
    return apply(lambda a, m, v: jnp.where(m, jnp.asarray(v, a.dtype), a),
                 x, mask, value, op_name="masked_fill")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                 op_name="where")


def nonzero(x, as_tuple=False):
    a = np.asarray(as_array(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def _pad(a):
        p = list(pad)
        if len(p) == a.ndim * 2:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle short form: [left, right, top, bottom, front, back] —
            # the j-th pair pads the j-th spatial dim counted from the LAST
            # (W first, then H, then D), per data_format
            width = [(0, 0)] * a.ndim
            n = len(p) // 2
            if data_format.startswith("NC"):      # NCL/NCHW/NCDHW
                dims = [a.ndim - 1 - j for j in range(n)]
            else:                                  # NLC/NHWC/NDHWC
                dims = [a.ndim - 2 - j for j in range(n)]
            for j, d in enumerate(dims):
                width[d] = (p[2 * j], p[2 * j + 1])
        if mode == "constant":
            return jnp.pad(a, width, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(a, width, mode=jmode)
    return apply(_pad, x, op_name="pad")


# -- sort / search ---------------------------------------------------------

def sort(x, axis=-1, descending=False, name=None):
    def _sort(a):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out
    return apply(_sort, x, op_name="sort")


def argsort(x, axis=-1, descending=False, name=None):
    def _argsort(a):
        out = jnp.argsort(a, axis=axis, descending=descending)
        return out.astype(jnp.int32)
    return apply(_argsort, x, op_name="argsort", nondiff=True)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(as_array(k))
    def _topk(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(moved, k)
        else:
            v, i = jax.lax.top_k(-moved, k)
            v = -v
        return (jnp.moveaxis(v, -1, ax),
                jnp.moveaxis(i.astype(jnp.int32), -1, ax))
    return apply(_topk, x, op_name="topk")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        v = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis).astype(jnp.int32)
        vv = jnp.take(v, k - 1, axis=axis)
        ii = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vv = jnp.expand_dims(vv, axis)
            ii = jnp.expand_dims(ii, axis)
        return vv, ii
    return apply(_kth, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(a):
        ax = axis % a.ndim
        srt = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax).astype(jnp.int32)
        pos = jnp.broadcast_to(
            jnp.arange(a.shape[ax]).reshape(
                [-1 if i == ax else 1 for i in range(a.ndim)]), a.shape)
        changed = jnp.concatenate(
            [jnp.ones_like(jnp.take(srt, jnp.asarray([0]), axis=ax),
                           dtype=bool),
             jnp.diff(srt, axis=ax) != 0], axis=ax)
        # run start index via cumulative max (associative), run len = pos-start
        start = jnp.where(changed, pos, 0)
        run_start = jax.lax.cummax(start, axis=ax)
        runs = pos - run_start
        # last index of the longest run (paddle returns the last occurrence)
        best = jnp.argmax(runs, axis=ax)
        vals = jnp.take_along_axis(srt, jnp.expand_dims(best, axis), axis=axis)
        inds = jnp.take_along_axis(idx, jnp.expand_dims(best, axis), axis=axis)
        if not keepdim:
            vals = jnp.squeeze(vals, axis)
            inds = jnp.squeeze(inds, axis)
        return vals, inds
    return apply(_mode, x, op_name="mode")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(as_array(x))
    res = np.unique(a, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    return apply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(
        jnp.int32 if out_int32 else jnp.int64),
        sorted_sequence, values, op_name="searchsorted", nondiff=True)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a, num_classes), x,
                 op_name="one_hot", nondiff=True)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply(lambda a: jnp.diff(a, n=n, axis=axis), x, op_name="diff")


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError(
        "as_strided has no XLA analog; use reshape/slice instead")


# -- tensor indexing (__getitem__/__setitem__ backends) --------------------

def _norm_index(idx):
    """Convert Tensors inside an index expression to arrays."""
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    if isinstance(idx, Tensor):
        return idx.data
    return idx


def getitem(x, idx):
    nidx = _norm_index(idx)
    return apply(lambda a: a[nidx], x, op_name="slice")


def setitem(x, idx, value):
    nidx = _norm_index(idx)
    def _set(a, v):
        return a.at[nidx].set(v.astype(a.dtype) if hasattr(v, "astype") else v)
    out = apply(_set, x, value, op_name="set_value")
    x._rebind(out)
    return x


def slice(input, axes, starts, ends, name=None):  # noqa: A001
    """reference: operators/slice_op.cc — slice along the given axes."""
    a = as_array(input)
    idx = [builtins_slice(None)] * a.ndim
    for ax, s, e in zip(axes, starts, ends):
        dim = a.shape[ax]
        s = int(s) if s >= 0 else int(s) + dim
        e = int(e) if e >= 0 else int(e) + dim
        idx[ax] = builtins_slice(max(s, 0), min(e, dim))
    return apply(lambda x: x[tuple(idx)], input, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    """reference: operators/strided_slice_op.cc."""
    a = as_array(x)
    idx = [builtins_slice(None)] * a.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        dim = a.shape[ax]
        s = int(s) if s >= 0 else int(s) + dim
        e = int(e) if e >= 0 else int(e) + dim
        idx[ax] = builtins_slice(s, e, int(st))
    return apply(lambda v: v[tuple(idx)], x, op_name="strided_slice")


def crop_tensor(x, shape=None, offsets=None, name=None):
    """reference: operators/crop_tensor_op.cc — crop ``shape`` starting at
    ``offsets`` (defaults: zero offsets, full shape)."""
    a = as_array(x)
    shape = list(shape if shape is not None else a.shape)
    offsets = list(offsets if offsets is not None else [0] * a.ndim)
    shape = [a.shape[i] - offsets[i] if s in (-1, None) else s
             for i, s in enumerate(shape)]
    idx = tuple(builtins_slice(o, o + s) for o, s in zip(offsets, shape))
    return apply(lambda v: v[idx], x, op_name="crop_tensor")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """reference: operators/shard_index_op.cc — recode global ids into a
    shard-local id space (the PS sharded-embedding helper): ids inside
    this shard's [shard_id*size, (shard_id+1)*size) window map to
    id - shard_id*size, everything else to ``ignore_value``."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for nshards {nshards}")
    size = (index_num + nshards - 1) // nshards

    def fn(ids):
        lo = shard_id * size
        inside = (ids >= lo) & (ids < lo + size)
        return jnp.where(inside, ids - lo, ignore_value)

    return apply(fn, input, op_name="shard_index", nondiff=True)


def scatter_(x, index, updates, overwrite=True, name=None):
    """In-place scatter (reference inplace op scatter_): mutates x.data."""
    out = scatter(x, index, updates, overwrite)
    x._rebind(out)
    return x


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._rebind(out)
    return x


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._rebind(out)
    return x
