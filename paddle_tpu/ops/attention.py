"""Paged attention: decode-phase attention over a page-table-indexed
KV cache (Ragged Paged Attention, PAPERS.md).

The serving KV cache (:mod:`paddle_tpu.serving.kv_cache`) stores every
sequence's keys/values in fixed-size *pages* drawn from one preallocated
pool; a per-sequence page table (int32 page indices) maps logical token
positions to physical pages.  Because the pool, the page tables, and the
query batch all have static shapes, ONE compiled decode kernel serves
any mix of ragged sequence lengths — raggedness lives in the *data*
(table entries + lengths), never in the *shapes*.

Two tiers, selected per call:

- **reference** (always available, any backend): gather the K/V pages by
  page table (``pool[page_table]``), flatten to the per-sequence logical
  KV view, mask positions ``>= length``, dense softmax attention.  This
  is the semantics oracle and the CPU/tier-1 path.
- **Pallas** (shape-gated hook): a registered TPU kernel takes over when
  :func:`paged_attention_supported` accepts the shapes AND a kernel has
  been installed via :func:`register_paged_attention_kernel`.  The gate
  mirrors ``ops/pallas/flash_attention.flash_attention_supported``
  (dtype/backed/tile-alignment checks: head dim a multiple of the
  128-lane register width, page size a multiple of the 8-sublane f32
  tile); the ragged-paged-attention kernel itself is the ROADMAP item 4
  Pallas tier — this hook is the socket it plugs into.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.flags import get_flag
from ..core.tensor import Tensor

__all__ = ["paged_attention", "paged_attention_reference",
           "paged_attention_select", "paged_attention_supported",
           "register_paged_attention_kernel"]

_NEG = -1e30

# the installed Pallas-tier kernel (None until ROADMAP item 4 lands or a
# test registers one); signature must match paged_attention_reference
_PALLAS_KERNEL: Optional[Callable] = None


def register_paged_attention_kernel(fn: Optional[Callable]) -> None:
    """Install (or clear, with ``None``) the Pallas-tier kernel.

    ``fn(q, k_pool, v_pool, page_table, lengths, scale) -> out`` with the
    same array contract as :func:`paged_attention_reference`.  Dispatch
    still goes through :func:`paged_attention_supported`; registering a
    kernel never affects unsupported shapes or non-TPU backends."""
    global _PALLAS_KERNEL
    _PALLAS_KERNEL = fn


def paged_attention_supported(q_shape, kv_pool_shape, dtype,
                              page_size: int) -> bool:
    """Shape gate for the Pallas tier (capability, not profitability).

    Requires an installed kernel, a TPU backend, f32/bf16, a head dim
    aligned to the 128-lane registers, and pages aligned to the 8-row
    f32 sublane tile — the layout the ragged-paged-attention kernel
    (ops/pallas/paged_attention.py) streams without relayout.  A 5-D
    [L, N, page, Hkv, D] pool is accepted for the per-layer ``layer=``
    dispatch the serving decode step uses.  Off TPU, a kernel that
    declares ``interpret_ok`` may still dispatch when the process opts
    into interpret-mode execution with ``FLAGS_pallas_interpret``
    (tests/bench only — interpret mode is not a performance path)."""
    if _PALLAS_KERNEL is None:
        return False
    if not get_flag("use_pallas_kernels"):
        return False
    if jax.default_backend() != "tpu":
        if not (getattr(_PALLAS_KERNEL, "interpret_ok", False)
                and get_flag("pallas_interpret")):
            return False
    if dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if len(q_shape) != 3 or len(kv_pool_shape) not in (4, 5):
        return False
    head_dim = q_shape[-1]
    if head_dim % 128 or head_dim != kv_pool_shape[-1]:
        return False
    if page_size % 8:
        return False
    return True


def _paged_attention_impl(q, k_pool, v_pool, page_table, lengths, *,
                          scale, layer=None):
    """Gather-by-page-table reference.

    q: [S, H, D] one query token per sequence slot;
    k_pool/v_pool: [N, page, Hkv, D] the shared physical page pool —
    or the full [L, N, page, Hkv, D] stack with ``layer`` set, in which
    case the layer index is composed INTO the page gather (one fused
    gather; slicing the layer out first would materialize it);
    page_table: [S, P] int32 physical page per logical page;
    lengths: [S] int32 valid KV length (the current token included).
    Returns [S, H, D].  H must be a multiple of Hkv (grouped-query
    attention broadcasts each KV head over H/Hkv query heads)."""
    S, H, D = q.shape
    page = k_pool.shape[-3]
    Hkv = k_pool.shape[-2]
    P = page_table.shape[1]
    T = P * page                                   # logical KV capacity
    # gather pages -> the per-sequence logical KV view [S, T, Hkv, D]
    if layer is not None:
        k = k_pool[layer, page_table].reshape(S, T, Hkv, D)
        v = v_pool[layer, page_table].reshape(S, T, Hkv, D)
    else:
        k = k_pool[page_table].reshape(S, T, Hkv, D)
        v = v_pool[page_table].reshape(S, T, Hkv, D)
    if Hkv != H:                                   # grouped-query attn
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = jnp.arange(T)[None, :] < lengths[:, None]       # [S, T]
    s = jnp.where(valid[:, None, :], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("sht,sthd->shd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                              scale=None, layer=None):
    """The always-available reference tier (raw jnp arrays in/out)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _paged_attention_impl(q, k_pool, v_pool, page_table, lengths,
                                 scale=float(scale), layer=layer)


def _kernel_takes_layer(fn) -> bool:
    """Whether the registered kernel accepts the ``layer=`` kwarg (the
    stacked-pool contract) — decided by signature inspection, NOT by
    catching TypeError from the call: JAX raises TypeError for genuine
    trace-time shape defects too, and swallowing those would silently
    degrade every decode step to the gather reference."""
    import inspect
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return "layer" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())


def paged_attention_select(q, k_pool, v_pool, page_table, lengths, *,
                           scale, layer=None):
    """Raw-array tier selection: the registered Pallas kernel when the
    gates accept these shapes (incl. per-layer dispatch over a stacked
    5-D pool), else the gather reference.  The serving decode step
    (serving/models.py) calls this inside its compiled step — the hook
    is what makes TPU decode gather-free without touching engine code.

    Two gates compose: the hook-level :func:`paged_attention_supported`
    (backend, dtype, tile alignment) and, when the registered kernel
    publishes one via a ``supported`` attribute, the kernel's own
    stricter capability check (e.g. whole GQA groups) — shapes either
    gate rejects take the reference tier cleanly."""
    pool_shape = tuple(k_pool.shape)
    kernel = _PALLAS_KERNEL
    if paged_attention_supported(tuple(q.shape), pool_shape, q.dtype,
                                 int(pool_shape[-3])):
        gate = getattr(kernel, "supported", None)
        if gate is not None and not gate(tuple(q.shape), pool_shape,
                                         q.dtype, int(pool_shape[-3])):
            pass  # kernel-side gate rejected: reference tier
        elif _kernel_takes_layer(kernel):
            return kernel(q, k_pool, v_pool, page_table, lengths,
                          scale=float(scale), layer=layer)
        elif layer is None:
            # a kernel registered against the PR-7 contract (no layer
            # kwarg) still serves the 4-D un-stacked case
            return kernel(q, k_pool, v_pool, page_table, lengths,
                          scale=float(scale))
    return _paged_attention_impl(q, k_pool, v_pool, page_table,
                                 lengths, scale=float(scale),
                                 layer=layer)


def paged_attention(q, k_pool, v_pool, page_table, lengths, scale=None,
                    layer=None, name=None):
    """Decode-phase paged attention (one query token per sequence).

    Accepts Tensors or arrays; records as op ``paged_attention`` in
    static Programs (priced by the cost model's attention rule).  See
    :func:`paged_attention_reference` for the array contract; ``layer``
    selects one layer of a stacked [L, N, page, Hkv, D] pool inside the
    gather.  The Pallas tier handles per-layer (4-D) pools."""
    q_arr = q.data if isinstance(q, Tensor) else jnp.asarray(q)
    if scale is None:
        scale = 1.0 / math.sqrt(q_arr.shape[-1])
    pool_shape = tuple((k_pool.data if isinstance(k_pool, Tensor)
                        else k_pool).shape)
    if paged_attention_supported(
            q_arr.shape, pool_shape, q_arr.dtype, int(pool_shape[-3])):
        return apply(paged_attention_select, q, k_pool, v_pool,
                     page_table, lengths, op_name="paged_attention",
                     nondiff=True, scale=float(scale), layer=layer)
    return apply(_paged_attention_impl, q, k_pool, v_pool, page_table,
                 lengths, op_name="paged_attention", nondiff=True,
                 scale=float(scale), layer=layer)
