"""paddle_tpu.ops — the op library.

One pure-functional op set shared by eager ("dygraph") execution and
``to_static``/jit tracing, mirroring how the reference's dygraph tracer and
static executor dispatch into one OpKernel registry (SURVEY §1, reference:
framework/operator.h:474).

``monkey_patch_tensor`` injects the op surface as Tensor methods and dunders,
the analog of the reference's varbase_patch_methods.py / math_op_patch.py.
"""
from __future__ import annotations

from .creation import *  # noqa: F401,F403
from .creation import to_tensor  # noqa: F401
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from . import linalg  # noqa: F401
from .linalg import norm, dist  # noqa: F401
from . import sequence  # noqa: F401
from . import attention  # noqa: F401
from .attention import (paged_attention,  # noqa: F401
                        paged_attention_supported,
                        register_paged_attention_kernel)

from ..core.tensor import Tensor
from ..core.dispatch import apply as _apply

from . import math as _math
from . import reduction as _red
from . import manipulation as _man
from . import logic as _logic
from . import creation as _cre


def _flip_args(fn):
    def flipped(x, y, name=None):
        return fn(y, x)
    return flipped


def monkey_patch_tensor():
    T = Tensor
    # arithmetic dunders
    T.__add__ = _math.add
    T.__radd__ = _flip_args(_math.add)
    T.__sub__ = _math.subtract
    T.__rsub__ = _flip_args(_math.subtract)
    T.__mul__ = _math.multiply
    T.__rmul__ = _flip_args(_math.multiply)
    T.__truediv__ = _math.divide
    T.__rtruediv__ = _flip_args(_math.divide)
    T.__floordiv__ = _math.floor_divide
    T.__rfloordiv__ = _flip_args(_math.floor_divide)
    T.__mod__ = _math.remainder
    T.__rmod__ = _flip_args(_math.remainder)
    T.__pow__ = _math.pow
    T.__rpow__ = _flip_args(_math.pow)
    T.__neg__ = _math.neg
    T.__abs__ = _math.abs
    T.__matmul__ = _math.matmul
    T.__rmatmul__ = _flip_args(_math.matmul)
    # comparisons
    T.__eq__ = _logic.equal
    T.__ne__ = _logic.not_equal
    T.__lt__ = _logic.less_than
    T.__le__ = _logic.less_equal
    T.__gt__ = _logic.greater_than
    T.__ge__ = _logic.greater_equal
    T.__hash__ = lambda self: id(self)
    # logical
    T.__and__ = _logic.bitwise_and
    T.__or__ = _logic.bitwise_or
    T.__xor__ = _logic.bitwise_xor
    T.__invert__ = _logic.bitwise_not
    # indexing
    T.__getitem__ = _man.getitem
    T.__setitem__ = _man.setitem

    methods = dict(
        # math
        add=_math.add, subtract=_math.subtract, multiply=_math.multiply,
        divide=_math.divide, floor_divide=_math.floor_divide,
        remainder=_math.remainder, mod=_math.remainder, pow=_math.pow,
        matmul=_math.matmul, mm=_math.mm, bmm=_math.bmm, dot=_math.dot,
        maximum=_math.maximum, minimum=_math.minimum,
        exp=_math.exp, log=_math.log, log2=_math.log2, log10=_math.log10,
        log1p=_math.log1p, sqrt=_math.sqrt, rsqrt=_math.rsqrt,
        square=_math.square, abs=_math.abs, sign=_math.sign,
        neg=_math.neg, floor=_math.floor, ceil=_math.ceil,
        round=_math.round, trunc=_math.trunc,
        sin=_math.sin, cos=_math.cos, tan=_math.tan, asin=_math.asin,
        acos=_math.acos, atan=_math.atan, sinh=_math.sinh, cosh=_math.cosh,
        tanh=_math.tanh, erf=_math.erf, sigmoid=_math.sigmoid,
        reciprocal=_math.reciprocal, scale=_math.scale, clip=_math.clip,
        lerp=_math.lerp, cumsum=_math.cumsum, cumprod=_math.cumprod,
        isnan=_math.isnan, isinf=_math.isinf, isfinite=_math.isfinite,
        trace=_math.trace, kron=_math.kron, outer=_math.outer,
        inner=_math.inner, cross=_math.cross, addmm=_math.addmm,
        nan_to_num=_math.nan_to_num, logaddexp=_math.logaddexp,
        # reduction
        sum=_red.sum, mean=_red.mean, max=_red.max, min=_red.min,
        amax=_red.amax, amin=_red.amin, prod=_red.prod,
        argmax=_red.argmax, argmin=_red.argmin, all=_red.all, any=_red.any,
        logsumexp=_red.logsumexp, std=_red.std, var=_red.var,
        median=_red.median, nanmean=_red.nanmean, nansum=_red.nansum,
        count_nonzero=_red.count_nonzero,
        # manipulation
        reshape=_man.reshape, reshape_=_man.reshape_,
        transpose=_man.transpose, t=_man.t, moveaxis=_man.moveaxis,
        swapaxes=_man.swapaxes, flatten=_man.flatten, squeeze=_man.squeeze,
        unsqueeze=_man.unsqueeze, split=_man.split, chunk=_man.chunk,
        unbind=_man.unbind, tile=_man.tile, expand=_man.expand,
        expand_as=_man.expand_as, broadcast_to=_man.broadcast_to,
        flip=_man.flip, roll=_man.roll,
        repeat_interleave=_man.repeat_interleave, gather=_man.gather,
        gather_nd=_man.gather_nd, take_along_axis=_man.take_along_axis,
        put_along_axis=_man.put_along_axis, scatter=_man.scatter,
        scatter_nd_add=_man.scatter_nd_add, index_select=_man.index_select,
        index_sample=_man.index_sample, masked_select=_man.masked_select,
        masked_fill=_man.masked_fill, where=_man.where,
        nonzero=_man.nonzero, sort=_man.sort, argsort=_man.argsort,
        topk=_man.topk, kthvalue=_man.kthvalue, unique=_man.unique,
        pad=_man.pad, diff=_man.diff, one_hot=_man.one_hot,
        # logic
        equal=_logic.equal, not_equal=_logic.not_equal,
        greater_than=_logic.greater_than,
        greater_equal=_logic.greater_equal, less_than=_logic.less_than,
        less_equal=_logic.less_equal, logical_and=_logic.logical_and,
        logical_or=_logic.logical_or, logical_xor=_logic.logical_xor,
        logical_not=_logic.logical_not, isclose=_logic.isclose,
        allclose=_logic.allclose, equal_all=_logic.equal_all,
        isin=_logic.isin,
        # linalg
        norm=linalg.norm, dist=linalg.dist, cholesky=linalg.cholesky,
        inverse=linalg.inverse, matrix_power=linalg.matrix_power,
        # creation-ish
        tril=_cre.tril, triu=_cre.triu, diag=_cre.diag,
    )
    for name, fn in methods.items():
        setattr(T, name, fn)
