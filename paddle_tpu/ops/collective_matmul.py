"""Fused compute-collective matmul lowerings for tensor parallelism.

An mp-sharded matmul has two canonical forms (Megatron's column/row
split; "Optimizing Distributed ML Communication with Fused
Computation-Collective Operations" motivates fusing the collective INTO
the matmul so chunk transfers overlap chunk compute):

- **column-parallel** — the weight is sharded on its OUTPUT (non-
  contracting) dim: ``y = x @ all_gather(w)``.  Because the gather dim
  never enters the contraction, the fused per-chunk form — rotate the
  shards around the ring with ``ppermute``, matmul each chunk as it
  arrives, place its column block — is **bitwise identical** to the
  unfused gather-then-matmul sequence: each output column block is the
  very same ``x @ w_j`` dot, same contraction order over K.  That makes
  the composite correct on every backend and oracle-testable.
- **row-parallel** — the weight is sharded on its INPUT (contracting)
  dim: each rank holds a partial product and the results
  reduce-scatter: ``y_mine = my rows of psum(x_part @ w_part)``.  The
  ring form accumulates partials in ascending absolute device order
  (:func:`paddle_tpu.distributed.grad_comm._ascending_sum`), which is
  bitwise-identical to ``psum`` + slice at fp32.

The composite lowering is the default everywhere.  Where shapes meet
the MXU tile gates and the Pallas tier is on
(:func:`paddle_tpu.ops.pallas.support.tier_enabled`), the per-chunk
matmul runs as the Pallas kernel
(:mod:`paddle_tpu.ops.pallas.collective_matmul`) — the selection counts
``pallas.selected.collective_matmul`` and rides ``record_compile
(kernels=)`` like every other tier kernel.  The static Executor's
hybrid grad path lowers whole-layer gathers through the same machinery
(``grad_comm.gather_param`` + the layer's own matmul + chunk-keep at
the shard_map boundary) and records the lowering on its compile
record; calling these entry points directly is how custom layers opt
into the finer-grained per-chunk overlap.

Call these INSIDE shard_map over the mesh axis that shards the weight.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..distributed.grad_comm import (_ascending_sum, _chunked_all_to_all,
                                     gather_param)

__all__ = ["all_gather_matmul", "matmul_reduce_scatter",
           "lowering_label"]


def _chunk_mm(x, w):
    """One column chunk's matmul — the Pallas tier kernel when enabled
    and the shapes meet the tile gates, else the plain jnp matmul (the
    same op the unfused sequence lowers to, keeping the composite path
    bitwise vs its oracle)."""
    from .pallas.support import tier_enabled
    if tier_enabled() and x.ndim == 2:
        from .pallas.collective_matmul import (chunk_matmul,
                                               chunk_matmul_supported)
        if chunk_matmul_supported(x.shape, w.shape, x.dtype, w.dtype):
            return chunk_matmul(x, w)
    return jnp.matmul(x, w)


def lowering_label() -> str:
    """Which per-chunk matmul form the tier would select right now —
    for compile-record attribution (``kernels=``)."""
    from .pallas.support import tier_enabled
    return "pallas" if tier_enabled() else "composite"


def all_gather_matmul(x, w, axis_name: str, axis_size: int, *,
                      ring: bool = True):
    """Column-parallel fused all_gather+matmul: ``w`` is this rank's
    ``[K, N/size]`` shard of a weight sharded on its output dim over
    ``axis_name``; returns the full ``x @ W`` (``[..., N]``), bitwise
    equal to ``jnp.matmul(x, gather_param(w, ...))``.

    ``ring=True`` (default) rotates the shards with ``size-1``
    single-chunk ppermutes and matmuls each chunk as it arrives — the
    fused compute-collective form, giving even a static scheduler
    independent units to interleave.  ``ring=False`` is the unfused
    gather-then-matmul sequence (one collective for the latency-hiding
    scheduler to split)."""
    size = int(axis_size)
    if size <= 1:
        return _chunk_mm(x, w)
    if not ring:
        return jnp.matmul(
            x, gather_param(w, axis_name, size, dim=w.ndim - 1))
    nc = w.shape[-1]
    out = jnp.zeros(x.shape[:-1] + (nc * size,),
                    jnp.result_type(x.dtype, w.dtype))
    idx = jax.lax.axis_index(axis_name)
    cur = w
    for step in range(size):
        # after `step` rotations device r holds shard (r + step) % size
        src = jax.lax.rem(idx + step, size)
        y = _chunk_mm(x, cur)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, y.astype(out.dtype), src * nc, axis=out.ndim - 1)
        if step < size - 1:
            perm = [(d, (d - 1) % size) for d in range(size)]
            cur = jax.lax.ppermute(cur, axis_name, perm)
    return out


def matmul_reduce_scatter(x, w, axis_name: str, axis_size: int, *,
                          ring: bool = True):
    """Row-parallel fused matmul+reduce_scatter: ``x`` is this rank's
    ``[M, K/size]`` activation slice, ``w`` its matching ``[K/size, N]``
    weight shard; returns this rank's ``[M/size, N]`` row block of the
    full product (``M % size == 0`` required).

    The ring form reduces partials in ascending absolute device order —
    bitwise-identical at fp32 to the unfused
    ``psum(x @ w)`` + row-slice oracle; ``ring=False`` leaves one fused
    ``psum_scatter`` for the latency-hiding scheduler."""
    size = int(axis_size)
    partial = _chunk_mm(x, w)
    if size <= 1:
        return partial
    m = partial.shape[0]
    if m % size:
        raise ValueError(
            f"matmul_reduce_scatter: leading dim {m} is not divisible "
            f"by axis size {size} — pad the batch or keep the matmul "
            f"column-parallel.")
    if ring:
        rows = partial.reshape((size, m // size) + partial.shape[1:])
        return _ascending_sum(
            _chunked_all_to_all(rows, axis_name, size), size)
    return jax.lax.psum_scatter(partial, axis_name,
                                scatter_dimension=0, tiled=True)
