"""Fused matmul-epilogue kernels (fwd + custom-vjp bwd).

The consumer side of the cost model's ranked fusion candidates
(static/analysis/cost.py `_fusion_candidates` — "the MPK-style feed for
the Pallas tier"): a single-consumer chain anchored on a ``linear`` op
whose epilogue is bias / gelu / relu / residual-add / layer_norm
compiles to ONE kernel that keeps the [M, N] intermediate in VMEM —
every fused stage saves the 2x HBM round-trip of its input exactly as
the candidate's ``saved_bytes`` prices it.  The TPU analog of the
reference's hand-fused epilogue ops (reference: operators/fused/
fused_gemm_epilogue_op.cu, fused_bias_residual_layernorm; the
ir/*_fuse_pass.cc chain matchers are the executor-side pass in
static/analysis/fusion.py).

Epilogue *stages* are a static recipe — a tuple of descriptors applied
in order to the f32 matmul accumulator:

- ``("bias",)``              adds a consumed [N] operand;
- ``("relu",)`` / ``("gelu", approximate)``   activation;
- ``("add",)``               adds a consumed [M, N] residual operand;
- ``("layer_norm", eps, has_w, has_b)``  row LN over the last dim,
  consuming the affine [N] operands its flags announce.

The backward is recompute-based (FlashAttention-style): one kernel
replays the forward chain from (x, w, operands) — the [M, N]
intermediates never hit HBM in either direction — then walks the
stages in reverse producing dx (blocked), dw / d-bias / d-affine
(accumulated across row blocks in f32), and d-residual (blocked).

Interpret mode (CPU) runs the same kernels for tests; the shape gate
(`fused_epilogue_supported`) mirrors the Mosaic tile constraints so
selection is identical on every backend.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .support import block_rows, dot as _dot, dtype_ok, \
    interpret_mode as _interpret_mode

__all__ = ["fused_linear_epilogue", "fused_epilogue_supported",
           "reference_epilogue", "stage_label"]

# VMEM budget for the weight block (staged whole per kernel; ~16 MB/core
# total must also hold x/dy/z blocks and the f32 dw accumulator)
_W_VMEM_CAP = 4 * 1024 * 1024

_SQRT_2 = math.sqrt(2.0)
_SQRT_2_OVER_PI = math.sqrt(2.0 / math.pi)
_INV_SQRT_2PI = 1.0 / math.sqrt(2.0 * math.pi)


def stage_label(stages) -> str:
    """Compact kernel name for records: ``matmul+bias+gelu`` etc."""
    return "+".join(["matmul"] + [s[0] for s in stages])


def _ops_per_stage(stage) -> int:
    """How many operands a stage consumes (in order)."""
    kind = stage[0]
    if kind in ("bias", "add"):
        return 1
    if kind == "layer_norm":
        return int(bool(stage[2])) + int(bool(stage[3]))
    return 0


def _gelu_f32(z, approximate):
    if approximate:
        u = _SQRT_2_OVER_PI * (z + 0.044715 * z * z * z)
        return 0.5 * z * (1.0 + jnp.tanh(u))
    return 0.5 * z * (1.0 + jax.lax.erf(z / _SQRT_2))


def _dgelu_f32(z, approximate):
    if approximate:
        u = _SQRT_2_OVER_PI * (z + 0.044715 * z * z * z)
        t = jnp.tanh(u)
        du = _SQRT_2_OVER_PI * (1.0 + 3.0 * 0.044715 * z * z)
        return 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * du
    cdf = 0.5 * (1.0 + jax.lax.erf(z / _SQRT_2))
    pdf = _INV_SQRT_2PI * jnp.exp(-0.5 * z * z)
    return cdf + z * pdf


def _ln_stats(h, eps):
    mu = jnp.mean(h, axis=-1, keepdims=True)
    d = h - mu
    var = jnp.mean(d * d, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return d * rstd, rstd


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------

def fused_epilogue_supported(x_shape, w_shape, dtype, stages=(),
                             operand_shapes=()) -> bool:
    """Capability gate, identical on every backend so the executor's
    selection is deterministic: Mosaic tile alignment (rows % 8,
    N % 128, K % 8), f32/bf16, the weight block within its VMEM
    budget, and every operand either the [N] per-feature vector or the
    full [M, N] residual its stage expects."""
    if not dtype_ok(dtype):
        return False
    if len(w_shape) != 2 or len(x_shape) < 2:
        return False
    k, n = int(w_shape[0]), int(w_shape[1])
    if int(x_shape[-1]) != k:
        return False
    m = 1
    for s in x_shape[:-1]:
        m *= int(s)
    if m <= 0 or m % 8 or k % 8 or n % 128:
        return False
    if k * n * 4 > _W_VMEM_CAP:  # f32 dw accumulator is the bound
        return False
    oi = 0
    for st in stages:
        kind = st[0]
        if kind not in ("bias", "relu", "gelu", "add", "layer_norm"):
            return False
        for _ in range(_ops_per_stage(st)):
            if oi >= len(operand_shapes):
                return False
            shp = tuple(int(s) for s in operand_shapes[oi])
            oi += 1
            want_full = kind == "add"
            if want_full:
                om = 1
                for s in shp[:-1]:
                    om *= int(s)
                if not shp or shp[-1] != n or om != m:
                    return False
            elif shp != (n,) and shp != (1, n):
                return False
    return oi == len(operand_shapes)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _apply_stages(z, stages, read_op):
    """Run the epilogue recipe over the f32 accumulator; ``read_op()``
    yields the next consumed operand (already f32, (1,N) or (bm,N)).
    Returns (result, [input value of each stage] for the backward)."""
    hs = []
    for st in stages:
        hs.append(z)
        kind = st[0]
        if kind in ("bias", "add"):
            z = z + read_op()
        elif kind == "relu":
            z = jnp.maximum(z, 0.0)
        elif kind == "gelu":
            z = _gelu_f32(z, st[1])
        elif kind == "layer_norm":
            _, eps, has_w, has_b = st
            z, _ = _ln_stats(z, eps)
            if has_w:
                z = z * read_op()
            if has_b:
                z = z + read_op()
    return z, hs


def _make_fwd_kernel(stages):
    def kernel(x_ref, w_ref, *rest):
        op_refs, o_ref = rest[:-1], rest[-1]
        it = iter(op_refs)

        def read_op():
            return next(it)[...].astype(jnp.float32)

        z = _dot(x_ref[...], w_ref[...], ((1,), (0,)))
        z, _ = _apply_stages(z, stages, read_op)
        o_ref[...] = z.astype(o_ref.dtype)

    return kernel


def _op_block_spec(shape, bm):
    if shape[0] == 1:  # (1, N) per-feature vector, shared by every block
        return pl.BlockSpec((1, shape[1]), lambda i: (0, 0))
    return pl.BlockSpec((bm, shape[1]), lambda i: (i, 0))


def _fwd(stages, interpret, x2, w, ops):
    m, k = x2.shape
    n = w.shape[1]
    bm = block_rows(m, 256)
    out = pl.pallas_call(
        _make_fwd_kernel(stages),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
        ] + [_op_block_spec(o.shape, bm) for o in ops],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        interpret=interpret,
    )(x2, w, *ops)
    return out


# ---------------------------------------------------------------------------
# backward (recompute-based)
# ---------------------------------------------------------------------------

def _make_bwd_kernel(stages, n_ops):
    # operand slot consumed by each stage, in forward order
    slots = []
    oi = 0
    for st in stages:
        cnt = _ops_per_stage(st)
        slots.append(tuple(range(oi, oi + cnt)))
        oi += cnt

    def kernel(x_ref, w_ref, dy_ref, *rest):
        op_refs = rest[:n_ops]
        dx_ref, dw_ref = rest[n_ops], rest[n_ops + 1]
        grad_refs = rest[n_ops + 2:]
        i = pl.program_id(0)

        # accumulated outputs (dw + every [1, N] operand grad) init once
        @pl.when(i == 0)
        def _init():
            dw_ref[...] = jnp.zeros(dw_ref.shape, dw_ref.dtype)
            for st, sl in zip(stages, slots):
                for j in sl:
                    if st[0] != "add":
                        grad_refs[j][...] = jnp.zeros(
                            grad_refs[j].shape, grad_refs[j].dtype)

        x = x_ref[...]
        w = w_ref[...]
        z = _dot(x, w, ((1,), (0,)))

        vals = [op_refs[j][...].astype(jnp.float32)
                for j in range(n_ops)]
        it = iter(range(n_ops))
        z_out, hs = _apply_stages(z, stages, lambda: vals[next(it)])
        del z_out

        g = dy_ref[...].astype(jnp.float32)
        for st, h_in, sl in reversed(list(zip(stages, hs, slots))):
            kind = st[0]
            if kind == "bias":
                grad_refs[sl[0]][...] += jnp.sum(g, 0, keepdims=True)
            elif kind == "add":
                grad_refs[sl[0]][...] = g.astype(grad_refs[sl[0]].dtype)
            elif kind == "relu":
                g = jnp.where(h_in > 0.0, g, 0.0)
            elif kind == "gelu":
                g = g * _dgelu_f32(h_in, st[1])
            elif kind == "layer_norm":
                _, eps, has_w, has_b = st
                xhat, rstd = _ln_stats(h_in, eps)
                si = 0
                if has_b:
                    grad_refs[sl[si + int(has_w)]][...] += jnp.sum(
                        g, 0, keepdims=True)
                if has_w:
                    grad_refs[sl[si]][...] += jnp.sum(
                        g * xhat, 0, keepdims=True)
                    g = g * vals[sl[si]]
                g = rstd * (g - jnp.mean(g, -1, keepdims=True)
                            - xhat * jnp.mean(g * xhat, -1,
                                              keepdims=True))
        dx_ref[...] = _dot(g.astype(w.dtype), w,
                           ((1,), (1,))).astype(dx_ref.dtype)
        dw_ref[...] += _dot(x, g.astype(x.dtype), ((0,), (0,)))

    return kernel


def _bwd_call(stages, interpret, x2, w, ops, dy):
    m, k = x2.shape
    n = w.shape[1]
    bm = block_rows(m, 256)
    grid = (m // bm,)
    # grads: dx blocked; dw accumulated f32; per-operand — (1, N)
    # operands accumulate in f32, [M, N] residuals are blocked
    out_shapes = [jax.ShapeDtypeStruct((m, k), x2.dtype),
                  jax.ShapeDtypeStruct((k, n), jnp.float32)]
    out_specs = [pl.BlockSpec((bm, k), lambda i: (i, 0)),
                 pl.BlockSpec((k, n), lambda i: (0, 0))]
    for o in ops:
        if o.shape[0] == 1:
            out_shapes.append(jax.ShapeDtypeStruct((1, n), jnp.float32))
            out_specs.append(pl.BlockSpec((1, n), lambda i: (0, 0)))
        else:
            out_shapes.append(jax.ShapeDtypeStruct((m, n), o.dtype))
            out_specs.append(pl.BlockSpec((bm, n), lambda i: (i, 0)))
    outs = pl.pallas_call(
        _make_bwd_kernel(stages, len(ops)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
        ] + [_op_block_spec(o.shape, bm) for o in ops],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(x2, w, dy, *ops)
    dx = outs[0]
    dw = outs[1].astype(w.dtype)
    dops = tuple(go.astype(o.dtype) for go, o in zip(outs[2:], ops))
    return dx, dw, dops


# ---------------------------------------------------------------------------
# custom-vjp core + public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused(stages, interpret, x2, w, ops):
    return _fwd(stages, interpret, x2, w, ops)


def _fused_fwd(stages, interpret, x2, w, ops):
    return _fwd(stages, interpret, x2, w, ops), (x2, w, ops)


def _fused_bwd(stages, interpret, res, dy):
    x2, w, ops = res
    return _bwd_call(stages, interpret, x2, w, ops, dy)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_linear_epilogue(x, w, bias=None, stages=(), operands=(),
                          interpret=None):
    """``epilogue(x @ w (+ bias))`` as one Pallas kernel.

    ``x``: [..., K]; ``w``: [K, N]; ``stages``: the post-bias epilogue
    recipe (see module docstring); ``operands``: arrays consumed by the
    ``add`` / ``layer_norm`` stages in order ([N] vectors or
    leading-dims-matching [..., N] residuals).  Leading dims flatten to
    the row dim around the kernel.  Differentiable in x, w, bias and
    every operand via the recompute-based backward kernel."""
    if interpret is None:
        interpret = _interpret_mode()
    k, n = int(w.shape[0]), int(w.shape[1])
    lead = tuple(x.shape[:-1])
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    stages_full = tuple(stages)
    ops = []
    if bias is not None:
        stages_full = (("bias",),) + stages_full
        ops.append(bias.reshape(1, n))
    it = iter(operands)
    for st in tuple(stages):
        for _ in range(_ops_per_stage(st)):
            o = next(it)
            ops.append(o.reshape(1, n) if o.ndim == 1 or o.shape == (1, n)
                       else o.reshape(m, n))
    from .support import count_kernel_selection
    count_kernel_selection("fused_epilogue")
    out = _fused(stages_full, bool(interpret), x2, w, tuple(ops))
    return out.reshape(lead + (n,))


# ---------------------------------------------------------------------------
# jnp oracle (the composite the kernel replaces, for tests/smoke)
# ---------------------------------------------------------------------------

def reference_epilogue(x, w, bias=None, stages=(), operands=()):
    """The unfused composite: same math via jnp/jax.nn, any backend."""
    z = jnp.matmul(x, w)
    if bias is not None:
        z = z + bias
    it = iter(operands)
    for st in stages:
        kind = st[0]
        if kind == "relu":
            z = jax.nn.relu(z)
        elif kind == "gelu":
            z = jax.nn.gelu(z, approximate=st[1])
        elif kind == "add":
            z = z + next(it)
        elif kind == "layer_norm":
            _, eps, has_w, has_b = st
            mu = jnp.mean(z, axis=-1, keepdims=True)
            var = jnp.var(z, axis=-1, keepdims=True)
            z = (z - mu) * jax.lax.rsqrt(var + eps)
            if has_w:
                z = z * next(it)
            if has_b:
                z = z + next(it)
        elif kind == "bias":
            z = z + next(it)
    return z
