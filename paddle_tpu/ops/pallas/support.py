"""Shared plumbing for the Pallas kernel tier.

Every kernel file (flash_attention, fused_epilogue, fused_adam,
paged_attention) needs the same four decisions made the same way:

- **backend**: ``pltpu`` import (absent on some CPU-only installs),
  interpret mode when not on a real TPU;
- **activation**: the tier is ON when ``FLAGS_use_pallas_kernels`` is
  set AND either the backend is TPU or ``FLAGS_pallas_interpret``
  explicitly opts a CPU process into interpret-mode execution (tests,
  bench, kernel_smoke — interpret mode is orders of magnitude slower
  than jnp, so it must never be the silent CPU default);
- **gates**: dtype and tile-alignment checks against the f32 (8, 128)
  sublane/lane tile;
- **observability**: every kernel SELECTION counts
  ``pallas.selected.<kernel>`` in monitor.  Selections happen at trace
  time (the kernel entry points run inside jitted programs, once per
  compile, then the baked executable dispatches without re-entering
  Python) — the counters say which kernels are compiled into the
  program, not how many times they executed; per-step volume belongs
  to the perf observatory.  "FLAGS off => zero selections" is the
  testable contract.

One place decides all four; the kernel files keep only their math.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["pltpu", "interpret_mode", "tier_enabled", "dtype_ok",
           "smem_scalar_spec", "count_kernel_selection",
           "kernel_selections", "block_rows", "NEG_INF"]

NEG_INF = -1e30


def dot(a, b, dims):
    """MXU matmul with f32 accumulation.  Precision is explicit: the
    global jax_default_matmul_precision=highest (used by tests) is not
    lowerable by Mosaic for bf16 operands; bf16 x bf16 -> f32 is the
    MXU-native path."""
    prec = (jax.lax.Precision.DEFAULT if a.dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=prec)


def interpret_mode() -> bool:
    """Pallas interpret mode: everywhere except a real TPU backend."""
    return jax.default_backend() != "tpu"


def tier_enabled() -> bool:
    """Should automatic paths (Executor fusion pass, fused Adam, the
    serving decode hook) select Pallas kernels right now?

    ``FLAGS_use_pallas_kernels`` is the master switch; off-TPU the tier
    additionally requires the explicit ``FLAGS_pallas_interpret`` opt-in
    — interpret mode exists for numerics tests, not for speed, so a CPU
    training run must never pay it by accident."""
    from ...core.flags import get_flag
    if not get_flag("use_pallas_kernels"):
        return False
    if jax.default_backend() == "tpu":
        return True
    return bool(get_flag("pallas_interpret"))


def dtype_ok(dtype) -> bool:
    """The two dtypes every tier kernel accumulates from (f32 math)."""
    return jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16))


def smem_scalar_spec():
    """(1, 1) scalar operand placed in SMEM on TPU (plain block spec in
    interpret mode / when pltpu is unavailable)."""
    if pltpu is not None:
        return pl.BlockSpec((1, 1), lambda *_: (0, 0),
                            memory_space=pltpu.SMEM)
    return pl.BlockSpec((1, 1), lambda *_: (0, 0))


# selection counter: {kernel name: trace-time selections} (see module
# docstring — compiles, not executions).  Tests assert the OFF contract
# (flag off => no entry moves); bench embeds the delta per suite.
kernel_selections: dict = {}


def count_kernel_selection(name: str) -> None:
    kernel_selections[name] = kernel_selections.get(name, 0) + 1
    from ...utils import monitor
    monitor.stat_add(f"pallas.selected.{name}")


def block_rows(m: int, preferred: int = 512) -> int:
    """Largest power-of-two row-block <= ``preferred`` that tiles ``m``
    (assumes ``m % 8 == 0``, the f32 sublane gate)."""
    bm = preferred
    while bm > 8 and m % bm:
        bm //= 2
    return max(min(bm, m), 1)
