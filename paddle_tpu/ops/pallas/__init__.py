"""Pallas TPU kernels — the hand-fused native tier.

These are the TPU analog of the reference's hand-written CUDA fusions
(reference: operators/math/bert_encoder_functor.cu multi-head attention,
operators/fused/, ir/*_fuse_pass.cc): where XLA's automatic fusion is not
enough (attention's softmax-rescale dataflow, the matmul-epilogue chains
the cost model ranks, the optimizer's multi-pass update), we write the
kernel by hand against the MXU/VMEM model.  Selection is behind
FLAGS_use_pallas_kernels with per-op capability checks (plus the
FLAGS_pallas_interpret opt-in off TPU); every kernel has an
interpret-mode path so the same code runs (slowly) on CPU in tests.

The tier:

- ``flash_attention``        — online-softmax attention, fwd + bwd;
- ``fused_linear_epilogue``  — matmul + bias/gelu/relu/residual/
  layer_norm epilogues off the cost model's ranked fusion candidates
  (selected by the static Executor's fusion pass);
- ``fused_adam_update``      — one-pass Adam over the donated
  ``_ExecState`` param/slot pairs;
- ``paged_attention_decode`` — gather-free paged decode attention
  behind ``ops.attention.register_paged_attention_kernel``.

Shared backend/gate/counter plumbing lives in ``support.py``.
"""
from .flash_attention import (flash_attention, flash_attention_supported,
                              mha_reference)
from .fused_adam import fused_adam_supported, fused_adam_update
from .fused_epilogue import (fused_epilogue_supported,
                             fused_linear_epilogue, reference_epilogue)
from .paged_attention import paged_attention_decode, paged_decode_supported
from .support import kernel_selections

__all__ = ["flash_attention", "flash_attention_supported", "mha_reference",
           "fused_adam_supported", "fused_adam_update",
           "fused_epilogue_supported", "fused_linear_epilogue",
           "reference_epilogue", "paged_attention_decode",
           "paged_decode_supported", "kernel_selections"]
