"""Pallas TPU kernels — the hand-fused native tier.

These are the TPU analog of the reference's hand-written CUDA fusions
(reference: operators/math/bert_encoder_functor.cu multi-head attention,
operators/fused/, ir/*_fuse_pass.cc): where XLA's automatic fusion is not
enough (attention's softmax-rescale dataflow), we write the kernel by hand
against the MXU/VMEM model.  Selection is behind FLAGS_use_pallas_kernels
with per-op capability checks; every kernel has an interpret-mode path so
the same code runs (slowly) on CPU in tests.
"""
from .flash_attention import (flash_attention, flash_attention_supported,
                              mha_reference)

__all__ = ["flash_attention", "flash_attention_supported", "mha_reference"]
