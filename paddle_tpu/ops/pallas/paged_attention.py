"""Pallas paged-attention decode kernel (Ragged Paged Attention).

The real kernel behind the shape-gated hook
``ops.attention.register_paged_attention_kernel`` that PR 7 left as a
socket: decode-phase attention (one query token per sequence slot)
over a page-table-indexed KV pool, with the page gather done by the
*grid pipeline* instead of an XLA gather.

Dataflow: grid ``(S, P)`` over (sequence slot, logical page) under a
``PrefetchScalarGridSpec`` — the page table and lengths are
scalar-prefetched, and the K/V BlockSpec index maps read
``table[s, p]``, so the pipeline DMAs exactly the physical page each
step needs from HBM into VMEM (gather-free: no [S, T, Hkv, D] logical
view ever materializes, which is what the reference tier pays).  The
online-softmax running (m, l, acc) state lives in VMEM scratch across
the page steps of one slot; positions past ``lengths[s]`` are masked,
so any mix of ragged context lengths shares one compiled kernel.
Grouped-query attention broadcasts each KV head over its query-head
group in-kernel.

Interpret mode (CPU) runs the same kernel for tests and bench;
automatic dispatch stays behind ``paged_attention_supported`` (TPU
backend, or the explicit ``FLAGS_pallas_interpret`` opt-in) plus the
existing tile-alignment gate.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .support import NEG_INF, dot as _dot, dtype_ok, \
    interpret_mode as _interpret_mode, pltpu

__all__ = ["paged_attention_decode", "paged_decode_supported",
           "register"]


def paged_decode_supported(q_shape, kv_pool_shape, dtype,
                           page_size: int) -> bool:
    """Kernel-side capability gate (mirrors ops.attention's hook gate):
    [S, H, D] queries, a 4-D [N, page, Hkv, D] pool or the stacked
    5-D [L, N, page, Hkv, D] one, f32/bf16, the 128-lane head dim and
    8-sublane page alignment, and whole GQA groups."""
    if not dtype_ok(dtype):
        return False
    if len(q_shape) != 3 or len(kv_pool_shape) not in (4, 5):
        return False
    s, h, d = (int(x) for x in q_shape)
    hkv = int(kv_pool_shape[-2])
    if s < 1 or d % 128 or d != int(kv_pool_shape[-1]):
        return False
    if h % max(hkv, 1):
        return False
    if int(page_size) % 8 or int(kv_pool_shape[-3]) != int(page_size):
        return False
    return True


def _decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page, hkv, group,
                   layered):
    s = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)

    q = q_ref[0].astype(jnp.float32) * scale            # [H, D]
    kv_block = (k_ref[0, 0], v_ref[0, 0]) if layered \
        else (k_ref[0], v_ref[0])                        # [page, Hkv, D]
    k_blk, v_blk = kv_block

    # logical positions of this page, masked by the slot's live length
    pos = p * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
    valid = pos < len_ref[s]                             # [1, page]

    # per-KV-head score/value rows (static python loop: Hkv is small on
    # decode models and Mosaic prefers 2-D dots over batched 3-D ones)
    score_rows = []
    for j in range(hkv):
        qj = q[j * group:(j + 1) * group, :]             # [G, D]
        kj = k_blk[:, j, :]                              # [page, D]
        score_rows.append(_dot(qj.astype(k_blk.dtype), kj,
                               ((1,), (1,))))            # [G, page]
    scores = jnp.concatenate(score_rows, axis=0)         # [H, page]
    scores = jnp.where(valid, scores, NEG_INF)

    m_prev = m_ref[...]                                  # [H, 1]
    l_prev = l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(scores, -1, keepdims=True))
    e = jnp.exp(scores - m_new)
    e = jnp.where(scores > 0.5 * NEG_INF, e, 0.0)        # fully masked
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(e, -1, keepdims=True)
    acc_rows = []
    for j in range(hkv):
        ej = e[j * group:(j + 1) * group, :]             # [G, page]
        vj = v_blk[:, j, :]                              # [page, D]
        acc_rows.append(_dot(ej.astype(v_blk.dtype), vj,
                             ((1,), (0,))))              # [G, D]
    acc_new = acc_ref[...] * alpha + jnp.concatenate(acc_rows, 0)
    m_ref[...] = m_new
    l_ref[...] = l_new
    acc_ref[...] = acc_new

    @pl.when(p == n_pages - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def paged_attention_decode(q, k_pool, v_pool, page_table, lengths,
                           scale=None, layer=None, interpret=None):
    """Gather-free decode attention; drop-in for
    ``ops.attention.paged_attention_reference`` (same array contract:
    q [S, H, D], pools [N, page, Hkv, D] — or [L, N, page, Hkv, D]
    with ``layer`` — page_table [S, P], lengths [S] -> out [S, H, D])."""
    if interpret is None:
        interpret = _interpret_mode()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    layered = layer is not None
    S, H, D = (int(x) for x in q.shape)
    page = int(k_pool.shape[-3])
    hkv = int(k_pool.shape[-2])
    group = H // hkv
    P = int(page_table.shape[1])
    table = jnp.asarray(page_table, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)

    if layered:
        li = int(layer)
        kv_spec = pl.BlockSpec(
            (1, 1, page, hkv, D),
            lambda s, p, t, l, _li=li: (_li, t[s, p], 0, 0, 0))
    else:
        kv_spec = pl.BlockSpec(
            (1, page, hkv, D), lambda s, p, t, l: (t[s, p], 0, 0, 0))

    if pltpu is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(S, P),
            in_specs=[
                pl.BlockSpec((1, H, D), lambda s, p, t, l: (s, 0, 0)),
                kv_spec, kv_spec,
            ],
            out_specs=pl.BlockSpec((1, H, D),
                                   lambda s, p, t, l: (s, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H, D), jnp.float32),
                pltpu.VMEM((H, 1), jnp.float32),
                pltpu.VMEM((H, 1), jnp.float32),
            ],
        )
        call = pl.pallas_call(
            functools.partial(_decode_kernel, scale=float(scale),
                              page=page, hkv=hkv, group=group,
                              layered=layered),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
            interpret=interpret,
        )
        out = call(table, lens, q, k_pool, v_pool)
    else:  # pragma: no cover - CPU-only installs without pltpu
        from ..attention import paged_attention_reference
        return paged_attention_reference(q, k_pool, v_pool, page_table,
                                         lengths, scale=scale,
                                         layer=layer)
    from .support import count_kernel_selection
    count_kernel_selection("paged_attention")
    return out


# marks for ops.attention's dispatcher: this kernel runs under
# interpret mode when FLAGS_pallas_interpret opts a CPU process in, and
# publishes its own (stricter) capability gate — paged_attention_select
# consults it on top of the hook-level gate, so shapes the kernel
# cannot carry (ragged GQA groups, mismatched page dims) take the
# reference tier instead of crashing at trace time
paged_attention_decode.interpret_ok = True
paged_attention_decode.supported = paged_decode_supported


def register() -> None:
    """Install this kernel behind the serving decode hook."""
    from ..attention import register_paged_attention_kernel
    register_paged_attention_kernel(paged_attention_decode)
