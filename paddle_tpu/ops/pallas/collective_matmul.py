"""Per-chunk matmul kernel for the fused collective-matmul lowerings.

The ring forms in :mod:`paddle_tpu.ops.collective_matmul` interleave
one chunk transfer with one chunk matmul per step; this kernel is the
compute half — a row-blocked MXU matmul over the chunk that just
arrived, so each ring step is one ``pallas_call`` the scheduler can
slot against the next ``ppermute``.  Communication stays in JAX
(ppermute between kernel invocations): Mosaic's cross-chip RDMA form
of the same loop is a later tier, and keeping the wire in JAX keeps
the composite's bitwise-vs-oracle property intact on every backend.

Shape gates follow the f32 (8, 128) sublane/lane tile: rows % 8 == 0,
contraction and chunk-column dims % 128 == 0.  Selection counts
``pallas.selected.collective_matmul`` (trace-time, like every tier
kernel).  Interpret mode (CPU) runs the same kernel for tests.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .support import block_rows, dot, dtype_ok, \
    interpret_mode as _interpret_mode

__all__ = ["chunk_matmul", "chunk_matmul_supported"]

_LANES = 128
_SUBLANES = 8


def chunk_matmul_supported(x_shape, w_shape, x_dtype, w_dtype) -> bool:
    """Tile-alignment + dtype gate: 2-D ``[M, K] @ [K, Nc]`` with M a
    sublane multiple and K, Nc lane multiples, f32/bf16 operands."""
    if len(x_shape) != 2 or len(w_shape) != 2:
        return False
    m, k = x_shape
    k2, nc = w_shape
    return (k == k2 and m % _SUBLANES == 0 and k % _LANES == 0
            and nc % _LANES == 0 and dtype_ok(x_dtype)
            and dtype_ok(w_dtype))


def _mm_kernel(x_ref, w_ref, o_ref):
    o_ref[...] = dot(x_ref[...], w_ref[...], ((1,), (0,)))


def chunk_matmul(x, w, *, interpret=None):
    """One chunk's ``x @ w`` as a row-blocked Pallas pass (f32
    accumulation).  Callers gate via :func:`chunk_matmul_supported`."""
    if interpret is None:
        interpret = _interpret_mode()
    m, _ = x.shape
    _, nc = w.shape
    bm = block_rows(m, 256)
    out = pl.pallas_call(
        _mm_kernel,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, x.shape[1]), lambda i: (i, 0)),
                  pl.BlockSpec((x.shape[1], nc), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, nc), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, nc), jnp.float32),
        interpret=interpret,
    )(x, w)
    from .support import count_kernel_selection
    count_kernel_selection("collective_matmul")
    return out.astype(jnp.result_type(x.dtype, w.dtype))
