"""Fused Adam update as one Pallas pass over each param/slot pair.

The unfused functional update (optimizer/optimizer.py Adam.update_param
under jax) is a chain of elementwise ops XLA usually — but not always —
fuses; each miss costs extra HBM round-trips over arrays the size of
the model.  This kernel makes the single-pass contract explicit: for
every donated ``_ExecState`` param, the (p, g, m, v) quartet is read
once and (p', m', v') written once, with the bias-corrected Adam math
in f32 registers in between (reference: operators/optimizers/adam_op.h
one-kernel-per-param functor; MPK's mega-kernelized optimizer stage).

Arrays of any shape ride the same kernel: flatten, zero-pad to the
f32 (8, 128) tile, update, slice back.  Padding is self-neutralizing
(g = m = v = 0 keeps p' = p - lr*0/(0+eps) = 0).

``fused_update_for`` is the static Executor's opt-in: it returns a
drop-in replacement for ``opt.functional_update`` only when the
optimizer is a plain f32 Adam whose semantics the kernel reproduces
exactly (no grad clip, no weight decay, no per-param lr, no
multi-precision master weights) — anything else stays on the composite
path.  Interpret mode (CPU) runs the same kernel for tests.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .support import block_rows, interpret_mode as _interpret_mode, \
    smem_scalar_spec

__all__ = ["fused_adam_update", "fused_adam_supported",
           "fused_update_for"]

_LANES = 128
_SUBLANES = 8


def fused_adam_supported(shape, dtype) -> bool:
    """f32 params only: Adam's slots are f32, and a bf16 param would
    take the master-weight path the kernel deliberately doesn't carry."""
    return jnp.dtype(dtype) == jnp.dtype(jnp.float32)


def _adam_kernel(lr_ref, step_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, beta1, beta2, eps):
    g = g_ref[...]
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * g * g
    step = step_ref[0, 0]
    # b^step via exp(step*log(b)) — the same lowering jnp uses for a
    # traced float exponent, so the trajectory matches the composite
    bc1 = 1.0 - jnp.exp(step * math.log(beta1))
    bc2 = 1.0 - jnp.exp(step * math.log(beta2))
    mhat = m / bc1
    vhat = v / bc2
    po_ref[...] = p_ref[...] - lr_ref[0, 0] * mhat / (jnp.sqrt(vhat) + eps)
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adam_update(p, g, m, v, lr, step, *, beta1=0.9, beta2=0.999,
                      eps=1e-8, interpret=None):
    """One-pass Adam: returns (p', m', v').  ``lr``/``step`` may be
    traced scalars (the executor's device-resident carry); betas/eps
    are static.  All four inputs must share p's shape; f32 only."""
    if interpret is None:
        interpret = _interpret_mode()
    shape = p.shape
    n = int(p.size)
    rows = max(-(-n // _LANES), 1)
    rows += (-rows) % _SUBLANES
    padded = rows * _LANES
    bm = block_rows(rows, 256)

    def flat(a):
        a = a.reshape(-1)
        if padded != n:
            a = jnp.pad(a, (0, padded - n))
        return a.reshape(rows, _LANES)

    lr2 = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    step2 = jnp.asarray(step, jnp.float32).reshape(1, 1)
    blk = pl.BlockSpec((bm, _LANES), lambda i: (i, 0))
    po, mo, vo = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=float(beta1),
                          beta2=float(beta2), eps=float(eps)),
        grid=(rows // bm,),
        in_specs=[smem_scalar_spec(), smem_scalar_spec(),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)] * 3,
        interpret=interpret,
    )(lr2, step2, flat(p), flat(g), flat(m), flat(v))

    def unflat(a):
        return a.reshape(-1)[:n].reshape(shape)

    from .support import count_kernel_selection
    count_kernel_selection("fused_adam")
    return unflat(po), unflat(mo), unflat(vo)


def fused_update_for(opt, params_meta, param_arrays):
    """A drop-in for ``opt.functional_update`` when — and only when —
    the kernel reproduces this optimizer's exact semantics, else None.

    Eligible: ``type(opt) is Adam`` (not AdamW/Lamb — decoupled decay
    and lr ratios live outside the kernel's math), no grad clip, no
    global or per-param regularizer, no multi-precision, no lazy mode,
    per-param lr multiplier 1, every param f32."""
    from ...optimizer.optimizer import Adam
    if type(opt) is not Adam:
        return None
    if opt._grad_clip is not None or opt._weight_decay is not None \
            or opt._multi_precision or opt._lazy:
        return None
    for meta in params_meta:
        if meta is None:
            continue
        if getattr(meta, "regularizer", None) is not None:
            return None
        if getattr(meta, "optimize_attr", {}).get(
                "learning_rate", 1.0) != 1.0:
            return None
    for arr in param_arrays:
        if not fused_adam_supported(arr.shape, arr.dtype):
            return None
    b1, b2, eps = opt._beta1, opt._beta2, opt._eps

    def update(param_arrays, grad_arrays, states, lr, step,
               params_meta=None):
        new_ps, new_ss = [], []
        for p, g, s in zip(param_arrays, grad_arrays, states):
            np_, nm, nv = fused_adam_update(
                p, g, s["m"], s["v"], lr, step,
                beta1=b1, beta2=b2, eps=eps)
            new_ps.append(np_)
            new_ss.append({"m": nm, "v": nv})
        return new_ps, new_ss

    return update
