"""Flash attention as a Pallas TPU kernel (fwd + custom-vjp bwd).

The TPU-native replacement for the reference's fused attention CUDA path
(reference: paddle/fluid/operators/math/bert_encoder_functor.cu
MultiHeadGPUComputeFunctor, operators/fused/fused_attention_op.cu,
ir/multihead_matmul_fuse_pass.cc): one kernel keeps Q/K/V blocks in VMEM,
streams KV, and carries the online-softmax running max/sum so the [L, L]
score matrix never touches HBM.

Layout: [B, L, H, D] in (paddle layout), transposed once to [B, H, L, D]
around the kernel.  Forward saves per-row logsumexp for the
recompute-based backward (standard FlashAttention-2 dataflow).

Causal masking supports traced *global position offsets* for Q and K
(`q_off`/`k_off`, float32 [1,1] scalars): a Q/K pair is visible when
``q_off + i >= k_off + j``.  Offsets are what lets ring attention
(parallel/ring_attention.py) reuse this kernel for every ring round —
rounds holding earlier shards fully visible, later shards fully masked,
the diagonal round causal — with ONE kernel instead of a lax.switch
(which custom_vjp cannot differentiate through).

Interpret mode (CPU) runs the same kernels for tests.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .support import (NEG_INF, dot as _dot, interpret_mode as _interpret,
                      pltpu, smem_scalar_spec as _smem_scalar_spec)


def flash_attention_supported(q_shape, k_shape, dtype, attn_mask=None,
                              dropout_p: float = 0.0,
                              block_q: int = 512, block_k: int = 512) -> bool:
    """Capability + profitability check: shapes/dtype the kernel handles
    AND where it beats XLA's fused attention (measured on v5e: flash wins
    ~30% at seq>=2048, XLA wins ~2% at seq 512 — the crossover is the
    FLAGS_pallas_attention_min_seqlen knob).  Attention dropout runs
    IN-KERNEL via the Pallas TPU PRNG (tile-seeded, regenerated in the
    backward) — but only on real TPUs (interpret mode has no PRNG)."""
    from ...core.flags import get_flag
    if attn_mask is not None:
        return False
    if dropout_p > 0.0 and _interpret():
        return False  # pltpu PRNG has no CPU interpreter lowering
    if len(q_shape) != 4:
        return False
    B, Lq, H, D = q_shape
    Lk = k_shape[1]
    if dtype not in (jnp.float32, jnp.bfloat16):
        return False
    min_len = get_flag("pallas_attention_dropout_min_seqlen"
                       if dropout_p > 0.0
                       else "pallas_attention_min_seqlen")
    if max(Lq, Lk) < min_len:
        return False
    # blocks must tile the sequence
    if Lq % min(block_q, Lq) or Lk % min(block_k, Lk):
        return False
    if D % 8:  # lane alignment of the head dim
        return False
    # whole-KV (and, in the dK/dV kernel, whole-Q) staging must fit VMEM
    # (~16 MB/core); beyond this the sequence belongs on the 'sp' ring
    itemsize = jnp.dtype(dtype).itemsize
    if max(Lq, Lk) * D * itemsize > 2 * 1024 * 1024:
        return False
    return True


def _mask_scores(s, causal, qi, j, q_off_ref, k_off_ref, block_q, block_k,
                 bq):
    if not causal:
        return s
    q_off = q_off_ref[0, 0]
    k_off = k_off_ref[0, 0]
    # int32 iota + cast: Mosaic's tpu.iota only produces integer vectors
    q_pos = (q_off + qi * block_q
             + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                        0).astype(jnp.float32))
    k_pos = (k_off + j * block_k
             + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k),
                                        1).astype(jnp.float32))
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _dropout_keep(seed_ref, qi, j, shape, dropout_p):
    """Tile keep-mask from the Pallas TPU PRNG, seeded on
    (user seed, b, h, q-block, k-block) so the backward kernels reproduce
    the forward's mask exactly.  prng_random_bits has int32 semantics on
    TPU: an arithmetic >>16 yields uniform [-32768, 32767], compared
    against the p-quantile threshold."""
    b = pl.program_id(0)
    h = pl.program_id(1)
    # Mosaic accepts at most 2 seed words: fold (b,h) and (qi,j) — the
    # 65599 strides keep tile seeds distinct for any h, j < 65599
    s1 = seed_ref[0, 0] ^ (b * 65599 + h)
    s2 = qi * 65599 + j
    pltpu.prng_seed(s1, s2)
    bits = pltpu.prng_random_bits(shape)
    v = jax.lax.shift_right_arithmetic(bits, 16)
    t = int(round(dropout_p * 65536.0)) - 32768
    return v >= t


def _apply_dropout(p, seed_ref, qi, j, dropout_p):
    """p (unnormalized probs) -> p * keep / (1 - p_q).  The softmax
    denominator keeps the UNdropped sum, which reproduces dropout applied
    to the normalized weights (out = sum(drop(w) v), w = p / l)."""
    if dropout_p <= 0.0:
        return p
    t = int(round(dropout_p * 65536.0))
    if t >= 65536:  # p ~ 1.0: everything drops
        return jnp.zeros_like(p)
    keep = _dropout_keep(seed_ref, qi, j, p.shape, dropout_p)
    inv_keep = 65536.0 / (65536 - t)
    return jnp.where(keep, p * inv_keep, 0.0)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_off_ref, k_off_ref, seed_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, *, scale, block_k, seq_k, causal, block_q,
                aligned, dropout_p):
    qi = pl.program_id(2)
    q_raw = q_ref[0, 0]
    q = (q_raw.astype(jnp.float32) * scale).astype(q_raw.dtype)  # [BQ, D]
    bq, d = q.shape
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal and aligned:
        # only blocks overlapping the causal triangle of this Q block
        num_kv = jnp.minimum(num_kv,
                             pl.cdiv((qi + 1) * block_q, block_k))

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]   # [BK, D]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, k, ((1,), (1,)))                      # [BQ, BK] f32
        s = _mask_scores(s, causal, qi, j, q_off_ref, k_off_ref, block_q,
                         block_k, bq)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # fully-masked rows: all s == NEG_INF makes s - m_new == 0; zero
        # those probabilities instead of attending uniformly
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
        alpha = jnp.exp(m - m_new)
        # denominator uses the UNdropped sum; only the value aggregation
        # sees the dropout mask (== dropout on normalized weights)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        u = _apply_dropout(p, seed_ref, qi, j, dropout_p)
        acc = acc * alpha + _dot(u.astype(v.dtype), v, ((1,), (0,)))
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    # lse block is (8, bq): positions on the LANE dim, replicated over 8
    # sublanes — the minimal Mosaic-legal tile.  A trailing unit dim
    # ([..., Lq, 1]) would make XLA tile-pad the HBM buffer 1 -> 128
    # lanes (128x memory — measured ~200 MB/layer residual at BERT-base
    # scale); the (bq, 1) -> (1, bq) relayout is a few hundred f32/block
    lse = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)
    lse_ref[0, 0] = jnp.broadcast_to(lse.reshape(1, -1), (8, lse.shape[0]))


def _qkv_fwd_specs(block_q, Lk, D):
    return [
        _smem_scalar_spec(),
        _smem_scalar_spec(),
        _smem_scalar_spec(),
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, Lk, D), lambda b, h, i: (b, h, 0, 0)),
    ]


def _fwd(q, k, v, q_off, k_off, seed, scale, causal, block_q, block_k,
         aligned, dropout_p=0.0):
    """q/k/v: [B, H, L, D] → (out [B,H,Lq,D], lse [B,H,Lq])."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    grid = (B, H, Lq // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                               seq_k=Lk, causal=causal, block_q=block_q,
                               aligned=aligned, dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=_qkv_fwd_specs(block_q, Lk, D),
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, h, i: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 8, Lq), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_off, k_off, seed, q, k, v)
    # compact [B, H, Lq] is the residual / public lse shape; the 8-sublane
    # replication exists only at the kernel boundary
    return out, lse[:, :, 0, :]


# ---------------------------------------------------------------------------
# backward (recompute-based, FlashAttention-2 style)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_off_ref, k_off_ref, seed_ref, q_ref, k_ref, v_ref,
                   do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_k,
                   seq_k, causal, block_q, aligned, dropout_p):
    qi = pl.program_id(2)
    q = q_ref[0, 0]                                       # [BQ, D]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][0:1, :].reshape(-1, 1)            # [BQ, 1]
    delta = delta_ref[0, 0][0:1, :].reshape(-1, 1)
    bq, d = q.shape
    dq = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal and aligned:
        num_kv = jnp.minimum(num_kv,
                             pl.cdiv((qi + 1) * block_q, block_k))

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, k, ((1,), (1,))) * scale
        s = _mask_scores(s, causal, qi, j, q_off_ref, k_off_ref, block_q,
                         block_k, bq)
        p = jnp.exp(s - lse)                              # [BQ, BK]
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
        u = _apply_dropout(p, seed_ref, qi, j, dropout_p)
        dp = _dot(do, v, ((1,), (1,)))
        # d s = p_norm * (keep_scale * dP - delta)  (see derivation in
        # _apply_dropout: the denominator is undropped)
        ds = (u * dp - p * delta) * scale
        return dq + _dot(ds.astype(k.dtype), k, ((1,), (0,)))

    dq = jax.lax.fori_loop(0, num_kv, body, dq)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_off_ref, k_off_ref, seed_ref, q_ref, k_ref, v_ref,
                    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale,
                    block_q, seq_q, causal, block_k, aligned, dropout_p):
    kj = pl.program_id(2)
    k = k_ref[0, 0]                                       # [BK, D]
    v = v_ref[0, 0]
    bk, d = k.shape
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    num_q = seq_q // block_q
    start = (kj * block_k) // block_q if (causal and aligned) else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, 0:1,
                      pl.ds(i * block_q, block_q)].reshape(-1, 1)
        delta = delta_ref[0, 0, 0:1,
                          pl.ds(i * block_q, block_q)].reshape(-1, 1)
        s = _dot(q, k, ((1,), (1,))) * scale
        # rows are q positions (loop index i), cols are this k block (kj)
        s = _mask_scores(s, causal, i, kj, q_off_ref, k_off_ref, block_q,
                         block_k, block_q)
        p = jnp.exp(s - lse)                              # [BQ, BK]
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
        # fwd tile (qi=i, j=kj): identical seed -> identical mask
        u = _apply_dropout(p, seed_ref, i, kj, dropout_p)
        dv = dv + _dot(u.astype(do.dtype), do, ((0,), (0,)))
        dp = _dot(do, v, ((1,), (1,)))
        ds = (u * dp - p * delta) * scale                 # [BQ, BK]
        dk = dk + _dot(ds.astype(q.dtype), q, ((0,), (0,)))
        return dk, dv

    dk, dv = jax.lax.fori_loop(start, num_q, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, q_off, k_off, seed, out, lse, do, dlse, scale, causal,
         block_q, block_k, aligned, dropout_p=0.0):
    """Full backward.  The lse cotangent folds into delta: with
    P = exp(S - lse) row-normalized, dS = P * (dP_rows - delta + dlse)
    since d lse / dS = P."""
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                              # [B, H, Lq]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # 8-sublane replication at the kernel boundary (see _fwd_kernel note)
    lse8 = jnp.broadcast_to(lse[:, :, None, :], (B, H, 8, Lq))
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (B, H, 8, Lq))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k,
                          seq_k=Lk, causal=causal, block_q=block_q,
                          aligned=aligned, dropout_p=dropout_p),
        grid=(B, H, Lq // block_q),
        in_specs=_qkv_fwd_specs(block_q, Lk, D) + [
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, h, i: (b, h, 0, i)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, h, i: (b, h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Lq, D), q.dtype),
        interpret=_interpret(),
    )(q_off, k_off, seed, q, k, v, do, lse8, delta8)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          seq_q=Lq, causal=causal, block_k=block_k,
                          aligned=aligned, dropout_p=dropout_p),
        grid=(B, H, Lk // block_k),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            pl.BlockSpec((1, 1, Lq, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, Lq, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 8, Lq), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 8, Lq), lambda b, h, j: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Lk, D), v.dtype),
        ],
        interpret=_interpret(),
    )(q_off, k_off, seed, q, k, v, do, lse8, delta8)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp cores over [B, H, L, D]
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _flash(q, k, v, q_off, k_off, seed, scale, causal, block_q, block_k,
           aligned, dropout_p):
    out, _ = _fwd(q, k, v, q_off, k_off, seed, scale, causal, block_q,
                  block_k, aligned, dropout_p)
    return out


def _flash_fwd(q, k, v, q_off, k_off, seed, scale, causal, block_q,
               block_k, aligned, dropout_p):
    out, lse = _fwd(q, k, v, q_off, k_off, seed, scale, causal, block_q,
                    block_k, aligned, dropout_p)
    return out, (q, k, v, q_off, k_off, seed, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, aligned, dropout_p, res,
               do):
    q, k, v, q_off, k_off, seed, out, lse = res
    dq, dk, dv = _bwd(q, k, v, q_off, k_off, seed, out, lse, do, None,
                      scale, causal, block_q, block_k, aligned, dropout_p)
    return (dq, dk, dv, jnp.zeros_like(q_off), jnp.zeros_like(k_off),
            None)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_with_lse(q, k, v, q_off, k_off, scale, block_q, block_k):
    """Position-masked attention returning (out, lse) — the ring-attention
    building block (both outputs differentiable; no dropout: ring rounds
    merge via logsumexp, which requires undropped weights)."""
    return _fwd(q, k, v, q_off, k_off, _zero_seed(), scale, True, block_q,
                block_k, False)


def _flash_with_lse_fwd(q, k, v, q_off, k_off, scale, block_q, block_k):
    out, lse = _fwd(q, k, v, q_off, k_off, _zero_seed(), scale, True,
                    block_q, block_k, False)
    return (out, lse), (q, k, v, q_off, k_off, out, lse)


def _flash_with_lse_bwd(scale, block_q, block_k, res, cts):
    q, k, v, q_off, k_off, out, lse = res
    do, dlse = cts
    dq, dk, dv = _bwd(q, k, v, q_off, k_off, _zero_seed(), out, lse, do,
                      dlse, scale, True, block_q, block_k, False)
    return dq, dk, dv, jnp.zeros_like(q_off), jnp.zeros_like(k_off)


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


# ---------------------------------------------------------------------------
# public entries
# ---------------------------------------------------------------------------

def _zero_off():
    return jnp.zeros((1, 1), jnp.float32)


def _zero_seed():
    return jnp.zeros((1, 1), jnp.int32)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = 512, block_k: int = 512,
                    dropout_p: float = 0.0, seed=None):
    """q/k/v: [B, L, H, D] arrays → [B, Lq, H, D] attention output.

    ``dropout_p > 0`` applies attention-probability dropout IN-KERNEL
    (Pallas TPU PRNG, tile-seeded from ``seed`` so the backward
    regenerates the identical mask); pass a fresh int32 ``seed`` array
    ([1, 1]) per training step."""
    D = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if dropout_p > 0.0 and (_interpret() or pltpu is None):
        raise NotImplementedError(
            "flash_attention dropout needs the Pallas TPU PRNG (real TPU "
            "only); use scaled_dot_product_attention, whose dispatch "
            "falls back to the unfused path off-TPU")
    if seed is None:
        seed = _zero_seed()
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    qt = jnp.swapaxes(q, 1, 2)      # [B, H, L, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, _zero_off(), _zero_off(), seed, scale,
                 bool(causal), block_q, block_k, True,
                 float(dropout_p))
    return jnp.swapaxes(out, 1, 2)


def flash_attention_block(q_bhld, k_bhld, v_bhld, q_off, k_off, scale,
                          block_q: int = 512, block_k: int = 512):
    """Ring-attention building block: [B, H, L, D] layout, traced global
    position offsets (float32 [1,1] arrays), always position-masked.
    Returns (out normalized [B,H,L,D], lse [B,H,L]); fully-masked rows
    give out=0, lse≈-inf — ready for logsumexp merging across rounds."""
    block_q = min(block_q, q_bhld.shape[2])
    block_k = min(block_k, k_bhld.shape[2])
    return _flash_with_lse(q_bhld, k_bhld, v_bhld, q_off, k_off, scale,
                           block_q, block_k)


def mha_reference(q, k, v, causal=False, scale=None):
    """jnp oracle for tests ([B, L, H, D] layout)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum("blhd,bshd->bhls", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Lq, Lk), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhls,bshd->blhd", w, v.astype(jnp.float32)
                      ).astype(q.dtype)
