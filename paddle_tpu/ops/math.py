"""Elementwise + linalg math ops (reference: python/paddle/tensor/math.py,
paddle/fluid/operators/elementwise/*, operators/matmul_v2_op.*).

Each public op wraps a pure jnp function through :func:`core.dispatch.apply`;
XLA fuses the elementwise zoo into surrounding matmuls on TPU, which replaces
the reference's hand-written fusion passes (ir/*_fuse_pass.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply, as_array
from ..core.tensor import Tensor

_prec = None  # set via flags/matmul_precision if needed


def _binop(jfn, name):
    def op(x, y, name=None):
        return apply(jfn, x, y, op_name=name, cacheable=True)
    op.__name__ = name
    return op


def _unop(jfn, name):
    def op(x, name=None):
        return apply(jfn, x, op_name=name, cacheable=True)
    op.__name__ = name
    return op


add = _binop(jnp.add, "add")
subtract = _binop(jnp.subtract, "subtract")
multiply = _binop(jnp.multiply, "multiply")
divide = _binop(jnp.divide, "divide")
floor_divide = _binop(jnp.floor_divide, "floor_divide")
remainder = _binop(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
maximum = _binop(jnp.maximum, "maximum")
minimum = _binop(jnp.minimum, "minimum")
fmax = _binop(jnp.fmax, "fmax")
fmin = _binop(jnp.fmin, "fmin")
atan2 = _binop(jnp.arctan2, "atan2")
hypot = _binop(jnp.hypot, "hypot")

exp = _unop(jnp.exp, "exp")
expm1 = _unop(jnp.expm1, "expm1")
log = _unop(jnp.log, "log")
log2 = _unop(jnp.log2, "log2")
log10 = _unop(jnp.log10, "log10")
log1p = _unop(jnp.log1p, "log1p")
sqrt = _unop(jnp.sqrt, "sqrt")
rsqrt = _unop(jax.lax.rsqrt, "rsqrt")
square = _unop(jnp.square, "square")
abs = _unop(jnp.abs, "abs")
sign = _unop(jnp.sign, "sign")
neg = _unop(jnp.negative, "neg")
floor = _unop(jnp.floor, "floor")
ceil = _unop(jnp.ceil, "ceil")
round = _unop(jnp.round, "round")
trunc = _unop(jnp.trunc, "trunc")
frac = _unop(lambda a: a - jnp.trunc(a), "frac")
sin = _unop(jnp.sin, "sin")
cos = _unop(jnp.cos, "cos")
tan = _unop(jnp.tan, "tan")
asin = _unop(jnp.arcsin, "asin")
acos = _unop(jnp.arccos, "acos")
atan = _unop(jnp.arctan, "atan")
sinh = _unop(jnp.sinh, "sinh")
cosh = _unop(jnp.cosh, "cosh")
tanh = _unop(jnp.tanh, "tanh")
asinh = _unop(jnp.arcsinh, "asinh")
acosh = _unop(jnp.arccosh, "acosh")
atanh = _unop(jnp.arctanh, "atanh")
erf = _unop(jax.scipy.special.erf, "erf")
erfinv = _unop(jax.scipy.special.erfinv, "erfinv")
sigmoid = _unop(jax.nn.sigmoid, "sigmoid")
reciprocal = _unop(jnp.reciprocal, "reciprocal")
digamma = _unop(jax.scipy.special.digamma, "digamma")
lgamma = _unop(jax.scipy.special.gammaln, "lgamma")
isnan = _unop(jnp.isnan, "isnan")
isinf = _unop(jnp.isinf, "isinf")
isfinite = _unop(jnp.isfinite, "isfinite")
conj = _unop(jnp.conj, "conj")
real = _unop(jnp.real, "real")
imag = _unop(jnp.imag, "imag")
angle = _unop(jnp.angle, "angle")


def pow(x, y, name=None):
    return apply(jnp.power, x, y, op_name="pow")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference: operators/scale_op.cc semantics."""
    def _scale(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out
    out = apply(_scale, x, scale, bias, op_name="scale")
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0, name=None):
    out = apply(lambda a: a + value, x, op_name="increment")
    x._rebind(out)
    return x


def clip(x, min=None, max=None, name=None):
    return apply(lambda a: jnp.clip(a, as_array(min) if min is not None else None,
                                    as_array(max) if max is not None else None),
                 x, op_name="clip")


def lerp(x, y, weight, name=None):
    return apply(lambda a, b, w: a + w * (b - a), x, y, weight, op_name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


def rad2deg(x, name=None):
    return apply(jnp.rad2deg, x, op_name="rad2deg")


def deg2rad(x, name=None):
    return apply(jnp.deg2rad, x, op_name="deg2rad")


def multiplex(inputs, index, name=None):
    def _mpx(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]
    return apply(_mpx, index, *inputs, op_name="multiplex")


# -- matmul family ---------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """reference: operators/matmul_v2_op.* — maps straight onto the MXU."""
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply(_matmul, x, y, op_name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return apply(jnp.matmul, x, y, op_name="bmm")


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot")


def inner(x, y, name=None):
    return apply(jnp.inner, x, y, op_name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                 input, x, y, op_name="addmm")


def kron(x, y, name=None):
    return apply(jnp.kron, x, y, op_name="kron")


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None
    def _cross(a, b):
        axx = ax
        if axx is None:
            for i, d in enumerate(a.shape):
                if d == 3:
                    axx = i
                    break
        return jnp.cross(a, b, axis=axx)
    return apply(_cross, x, y, op_name="cross")


def einsum(equation, *operands):
    return apply(lambda *xs: jnp.einsum(equation, *xs), *operands,
                 op_name="einsum")


# -- cumulative ------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def _cumsum(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=d)
        return jnp.cumsum(a, axis=axis, dtype=d)
    return apply(_cumsum, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    return apply(lambda a: jnp.cumprod(a, axis=dim, dtype=d), x,
                 op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    def _cummax(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        return vals
    return apply(_cummax, x, op_name="cummax")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def _lcse(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.cumlogsumexp(arr, axis=ax)
    return apply(_lcse, x, op_name="logcumsumexp")


def logaddexp(x, y, name=None):
    return apply(jnp.logaddexp, x, y, op_name="logaddexp")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                     axis2=axis2), x, op_name="trace")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), x,
                 op_name="nan_to_num")


def add_n(inputs, name=None):
    """reference: operators/sum_op.cc — elementwise sum of a tensor list."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    def fn(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out

    return apply(fn, *inputs, op_name="add_n")


def mv(x, vec, name=None):
    """reference: operators/mv_op.cc — matrix @ vector."""
    return apply(lambda a, v: a @ v, x, vec, op_name="mv")


def tanh_(x, name=None):
    """Inplace tanh (reference inplace op tanh_)."""
    out = tanh(x)
    x._rebind(out)
    return x


def broadcast_shape(x_shape, y_shape):
    """reference: tensor/manipulation broadcast_shape."""
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(input, name=None):
    """Tensor rank as a 0-d int tensor (tensor/attribute.py rank)."""
    from ..core.dispatch import as_array
    return Tensor(jnp.asarray(as_array(input).ndim, jnp.int32))


def shape(input, name=None):
    """Runtime shape as a 1-d int tensor (tensor/attribute.py shape)."""
    from ..core.dispatch import as_array
    return Tensor(jnp.asarray(as_array(input).shape, jnp.int32))
