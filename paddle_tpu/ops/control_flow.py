"""Control-flow ops: cond / case / switch_case / while_loop.

TPU-native re-design of the reference's control-flow operator suite
(reference: python/paddle/fluid/layers/control_flow.py cond:2326,
while_loop:1072, case:3075, switch_case:3191; C++ lowering in
paddle/fluid/operators/controlflow/ conditional_block_op.cc, while_op.cc).

Two execution regimes, matching the framework's dual-mode design:

- **Eager** (concrete predicate): evaluate the predicate on host and run
  ONLY the chosen branch with normal tape recording — fully differentiable,
  no wasted compute (the reference's conditional_block runs one block the
  same way).
- **Traced** (predicate is a jax tracer, i.e. inside ``paddle.jit.to_static``
  / ``TrainStep``): lower to ``lax.cond`` / ``lax.switch`` /
  ``lax.while_loop`` so the compiled program carries real data-dependent
  control flow.  ``cond``/``case``/``switch_case`` are reverse-mode
  differentiable; traced ``while_loop`` is forward-only (XLA's While has no
  reverse-mode adjoint — use a bounded loop or eager mode when you need
  gradients through a dynamic loop; the reference's while_grad replays the
  block stack, which XLA cannot express).

Python ``if``/``while`` on a traced Tensor raises a loud error pointing
here (core/tensor.py ``__bool__``) instead of silently freezing one branch
into the trace.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["cond", "case", "switch_case", "while_loop"]


def _is_static(x) -> bool:
    return getattr(type(x), "_static_var", False)


def _as_arr(x):
    if _is_static(x):
        from ..static.program import resolve_variable
        return resolve_variable(x)
    return x.data if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return isinstance(_as_arr(x), jax.core.Tracer)


def _unwrap(tree):
    return jax.tree.map(_as_arr, tree,
                        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(tree):
    return jax.tree.map(
        lambda a: Tensor(a) if isinstance(a, jnp.ndarray) else a, tree)


def _traced_branch(fn: Callable) -> Callable:
    """Wrap a user branch: run paddle ops inside, hand arrays to lax."""
    def run(*ops):
        out = fn(*_wrap(list(ops))) if ops else fn()
        return _unwrap(out)
    return run


def _bool_pred(pred):
    a = _as_arr(pred)
    if isinstance(a, jax.core.Tracer):
        return a
    return bool(a)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name=None,
         return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Reference: fluid/layers/control_flow.py:2326 (cond),
    operators/controlflow/conditional_block_op.cc.  Both branches must
    return the same structure of Tensors.  Differentiable in eager and
    traced mode (lax.cond has a reverse-mode rule).
    """
    true_fn = true_fn if true_fn is not None else (lambda: None)
    false_fn = false_fn if false_fn is not None else (lambda: None)
    if _is_static(pred):
        # record ONE composite node; branches replay at execution with
        # Variables resolved from the program env (single branch runs —
        # the reference's conditional_block semantics)
        def _cond_op(parr):
            return jax.lax.cond(
                jnp.asarray(parr).reshape(()).astype(jnp.bool_),
                _traced_branch(true_fn), _traced_branch(false_fn))
        return pred.program.record(_cond_op, [pred], {}, "cond")
    p = _bool_pred(pred)
    if not isinstance(p, jax.core.Tracer):
        return true_fn() if p else false_fn()
    out = jax.lax.cond(p, _traced_branch(true_fn),
                       _traced_branch(false_fn))
    return _wrap(out)


def case(pred_fn_pairs: Sequence[Tuple[Any, Callable]],
         default: Optional[Callable] = None, name=None):
    """First pair whose predicate is True wins (reference:
    fluid/layers/control_flow.py:3075).  Lowered to nested ``lax.cond`` in
    traced mode."""
    if not pred_fn_pairs:
        raise ValueError("case() expects at least one (pred, fn) pair")
    preds = [p for p, _ in pred_fn_pairs]
    if any(_is_static(p) for p in preds):
        tail0 = default if default is not None else pred_fn_pairs[-1][1]
        fns = [fn for _, fn in pred_fn_pairs]

        def _case_op(*pred_arrs):
            def build(i):
                if i == len(fns):
                    return _traced_branch(tail0)

                def branch():
                    return jax.lax.cond(
                        jnp.asarray(pred_arrs[i]).reshape(()).astype(
                            jnp.bool_),
                        _traced_branch(fns[i]), build(i + 1))
                return branch
            return build(0)()

        prog = next(p for p in preds if _is_static(p)).program
        return prog.record(_case_op, list(preds), {}, "case")
    if not any(_is_traced(p) for p in preds):
        for p, fn in pred_fn_pairs:
            if bool(_as_arr(p)):
                return fn()
        if default is not None:
            return default()
        # reference semantics: no default -> last fn
        return pred_fn_pairs[-1][1]()

    tail = default if default is not None else pred_fn_pairs[-1][1]

    def build(i):
        if i == len(pred_fn_pairs):
            return _traced_branch(tail)
        p, fn = pred_fn_pairs[i]

        def branch():
            return jax.lax.cond(jnp.asarray(_as_arr(p), jnp.bool_),
                                _traced_branch(fn), build(i + 1))
        return branch

    return _wrap(build(0)())


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """Dispatch on an integer index (reference:
    fluid/layers/control_flow.py:3191).  ``branch_fns`` is a list of fns,
    a list of (int, fn) pairs, or a {int: fn} dict; an out-of-range index
    runs ``default``.  Lowered to ``lax.switch`` in traced mode."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        branch_fns = list(branch_fns)
        if branch_fns and not callable(branch_fns[0]):
            pairs = sorted((int(k), fn) for k, fn in branch_fns)
        else:
            pairs = list(enumerate(branch_fns))
    keys = [k for k, _ in pairs]
    fns = [fn for _, fn in pairs]
    if default is None:
        default = fns[-1]  # reference semantics: fall back to the last fn

    if _is_static(branch_index):
        def _switch_op(idx_arr):
            pos = jnp.full((), len(keys), jnp.int32)
            for slot, k in enumerate(keys):
                pos = jnp.where(jnp.asarray(idx_arr).reshape(()) == k,
                                jnp.int32(slot), pos)
            branches = [_traced_branch(fn) for fn in fns]
            branches.append(_traced_branch(default))
            return jax.lax.switch(pos, branches)
        return branch_index.program.record(_switch_op, [branch_index], {},
                                           "switch_case")

    idx = _as_arr(branch_index)
    if not isinstance(idx, jax.core.Tracer):
        i = int(idx)
        return dict(pairs).get(i, default)()

    # position of idx among the keys; len(keys) = the default slot
    pos = jnp.full((), len(keys), jnp.int32)
    for slot, k in enumerate(keys):
        pos = jnp.where(idx == k, jnp.int32(slot), pos)
    branches = [_traced_branch(fn) for fn in fns]
    branches.append(_traced_branch(default))
    return _wrap(jax.lax.switch(pos, branches))


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None) -> List:
    """``while cond(*vars): vars = body(*vars)`` (reference:
    fluid/layers/control_flow.py:1072, operators/controlflow/while_op.cc).

    Eager: a Python loop with tape recording (differentiable, unrolled).
    Traced: ``lax.while_loop`` — shapes of loop_vars must be invariant and
    reverse-mode gradients are unsupported (XLA While has no adjoint).
    """
    if not callable(cond) or not callable(body):
        raise TypeError("while_loop expects callables for cond and body")
    loop_vars = list(loop_vars)

    def _static_while(prog):
        def _while_op(*arrs):
            def c(a):
                return jnp.asarray(_as_arr(cond(*_wrap(list(a))))
                                   ).reshape(()).astype(jnp.bool_)

            def b(a):
                out = body(*_wrap(list(a)))
                out = (list(out) if isinstance(out, (list, tuple))
                       else [out])
                return tuple(_unwrap(out))

            return tuple(jax.lax.while_loop(c, b, tuple(arrs)))

        return list(prog.record(_while_op, loop_vars, {}, "while_loop"))

    for v in loop_vars:
        if _is_static(v):
            return _static_while(v.program)

    probe = cond(*loop_vars)
    if _is_static(probe):
        # cond closed over a Program Variable (the probe recorded a stray
        # dead node — harmless): build the loop as a composite node
        return _static_while(probe.program)
    if not _is_traced(probe):
        # eager: genuine Python loop, tape sees every op.  The predicate
        # can BECOME traced mid-loop (a dy2static break/done flag fed by
        # a traced condition): iterations so far ran concretely, the
        # remainder continues as lax.while_loop from the current state.
        if not isinstance(probe, bool) and probe is not None:
            probe = bool(_as_arr(probe))
        while probe:
            out = body(*loop_vars)
            loop_vars = list(out) if isinstance(out, (list, tuple)) else [out]
            probe = cond(*loop_vars)
            if _is_traced(probe):
                break
            probe = bool(_as_arr(probe))
        if not _is_traced(probe):
            return loop_vars

    def c(arrs):
        return jnp.asarray(_as_arr(cond(*_wrap(arrs))), jnp.bool_)

    def b(arrs):
        out = body(*_wrap(arrs))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _unwrap(out)

    res = jax.lax.while_loop(c, b, _unwrap(loop_vars))
    return list(_wrap(res))
