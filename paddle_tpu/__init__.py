"""paddle_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design of the reference framework's
capability surface (see /root/repo/SURVEY.md): dual-mode execution (eager
"dygraph" + traced/compiled "static"), an nn.Layer system, optimizers, AMP,
data loading, and first-class SPMD distribution (DP/ZeRO/TP/PP/SP) over
``jax.sharding.Mesh``.

Public API mirrors the reference's ``paddle.*`` 2.0 surface so users can
switch with minimal changes; internals are idiomatic JAX, not a port.
"""
from __future__ import annotations

__version__ = "0.1.0"

# PRNG impl: 'rbg' (XLA RngBitGenerator for bits, threefry for split/fold_in)
# is ~10x cheaper than threefry on the TPU VPU — measured 84 ms/step of pure
# mask generation on the BERT-base bench.  Must be configured before the
# first jax.random.key() (core.rng builds the global Generator at import).
import os as _os

if "JAX_DEFAULT_PRNG_IMPL" not in _os.environ:
    import jax as _jax

    # respect an explicit programmatic choice; only replace jax's built-in
    # default ('threefry2x32' never set by a user who wanted rbg semantics
    # would be indistinguishable — documented limitation)
    if _jax.config.jax_default_prng_impl == "threefry2x32":
        _jax.config.update("jax_default_prng_impl",
                           _os.environ.get("FLAGS_prng_impl", "rbg"))

# latency-hiding scheduler knob: XLA_FLAGS is parsed exactly once, at
# backend creation, so FLAGS_xla_latency_hiding must act HERE — before
# the first device query anywhere below (core/xla_env.py appends only
# the target platform's scheduler flags; unknown flags are fatal to
# XLA's parser, so a CPU process never gets TPU flags appended)
from .core import xla_env as _xla_env  # noqa: E402

_xla_env.apply_latency_hiding_flags()

from .core import (Parameter, Tensor, enable_grad, get_default_dtype,  # noqa
                   get_flags, get_rng_state, grad, no_grad, seed,
                   set_default_dtype, set_flags, set_rng_state, to_tensor)
from .core.dtype import (bfloat16, bool_, complex64, complex128,  # noqa
                         float16, float32, float64, int8, int16, int32,
                         int64, uint8)

from . import ops  # noqa: E402
ops.monkey_patch_tensor()

# creation / random / manipulation / math / logic op surface at top level
from .ops import *  # noqa: F401,F403,E402
from .ops import linalg  # noqa: E402
from .ops.creation import to_tensor  # noqa: E402,F811

from .device import (device_count, get_device, is_compiled_with_cuda,  # noqa
                     is_compiled_with_tpu, is_compiled_with_xpu, set_device)
from .framework_io import load, save  # noqa: E402

from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import jit  # noqa: E402
from . import amp  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import distribution  # noqa: E402
from . import onnx  # noqa: E402
from . import vision  # noqa: E402
from . import text  # noqa: E402
from . import hapi  # noqa: E402
from .hapi import Model, summary  # noqa: E402
from . import distributed  # noqa: E402
from . import parallel  # noqa: E402
from . import static  # noqa: E402
from . import inference  # noqa: E402
from . import serving  # noqa: E402
from . import profiler  # noqa: E402
from . import observability  # noqa: E402
from . import utils  # noqa: E402
from . import quantization  # noqa: E402
from .parallel import DataParallel  # noqa: E402
from .optimizer import regularizer  # noqa: E402
from .nn.layer_base import ParamAttr  # noqa: E402

CPUPlace = "cpu"
TPUPlace = "tpu"

_static_mode = False


def disable_static(place=None):
    """Dygraph is the default mode; kept for API parity."""
    global _static_mode
    _static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def in_dynamic_mode():
    return not _static_mode


def is_grad_enabled():
    from .core import autograd
    return autograd.grad_enabled()


# -- round-4 top-level parity (reference: paddle/__init__.py aliases) ----
from .framework_compat import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa
                               TPUPlace, XPUPlace, create_parameter,
                               disable_dygraph, enable_dygraph, flops,
                               get_cuda_rng_state, get_cudnn_version,
                               in_dygraph_mode, set_cuda_rng_state,
                               set_printoptions)
from .hapi import callbacks  # noqa: E402,F401

# fleet telemetry: when the environment stages a spool dir (supervisors
# forward FLAGS_obs_spool_dir + a per-incarnation FLAGS_obs_role into
# every child they spawn), the exporter installs at import — a
# supervised child exports with zero code changes.  Unset (the normal
# case), this is one flag read.
from .core import flags as _flags  # noqa: E402

if _flags.get_flag("obs_spool_dir"):
    from .observability import export as _obs_export  # noqa: E402

    _obs_export.install_exporter()
from .ops.linalg import cholesky, histogram, inverse  # noqa: E402,F401
from .ops.manipulation import (crop_tensor, scatter_, shard_index,  # noqa
                               slice, squeeze_, strided_slice, unsqueeze_)
from .ops.math import (add_n, broadcast_shape, mv, rank, shape,  # noqa
                       tanh_)
