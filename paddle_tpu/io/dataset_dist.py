"""Distributed dataset with cross-worker global shuffle.

Reference: the fleet Dataset family —
``paddle/fluid/framework/data_set.h:43-211`` (InMemoryDataset,
``GlobalShuffle`` at :111, LocalShuffle :108), fed by DataFeed parsers
(``data_feed.h``) and created via
``python/paddle/fluid/dataset.py DatasetFactory``.

The reference's GlobalShuffle redistributes examples between trainers
through the parameter-server RPC fabric (brpc).  The TPU-native redesign
keeps the *capability* — every epoch, each worker ends up with a disjoint
1/N slice of a seed-deterministic global permutation of ALL examples —
but replaces the RPC fabric with the two channels a TPU pod actually has:

1. a **deterministic index protocol**: every worker computes the same
   global permutation ``pi = RandomState(seed).permutation(total)`` and
   the same contiguous position->worker chunking, so record routing needs
   no coordinator;
2. a **shared-filesystem spool** (GCS/NFS on real pods, tmpdir in tests)
   for the record payloads, with sentinel-file barriers.  Workers write
   one pickle per destination rank, then read the pickles addressed to
   them.  This is the pod-native analog of the reference's brpc
   ``SendVector``/barrier exchange and needs no sidecar process.

``load_into_memory`` honors the fleet file-shard convention
(``files[rank::world]`` — _FleetUtil.get_file_shard), so the pre-shuffle
load is already disjoint across workers.
"""
from __future__ import annotations

import glob as _glob
import hashlib
import json
import os
import pickle
import subprocess
import time

import numpy as np


__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


def _resolve_rank_world(rank=None, world_size=None):
    """Explicit args > launcher env > distributed.env helpers.

    The launcher env is checked first because datasets are often built
    before ``init_parallel_env`` (get_rank needs jax.distributed up);
    past that point the two sources agree by construction (the launcher
    sets both)."""
    if rank is not None and world_size is not None:
        return int(rank), int(world_size)
    env_r = os.environ.get("PADDLE_TRAINER_ID")
    env_w = os.environ.get("PADDLE_TRAINERS_NUM")
    if env_r is not None and env_w is not None:
        return int(env_r), int(env_w)
    from ..distributed.env import get_rank, get_world_size
    try:
        return get_rank(), get_world_size()
    except Exception:  # pragma: no cover - jax not initialised
        return 0, 1


def _wait_for(paths, timeout, what):
    deadline = time.monotonic() + timeout
    missing = list(paths)
    while missing:
        missing = [p for p in missing if not os.path.exists(p)]
        if not missing:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"global_shuffle: timed out waiting for {what}: "
                f"{missing[:4]}{'...' if len(missing) > 4 else ''}")
        time.sleep(0.02)


class _DatasetBase:
    """Shared config surface (reference: fluid/dataset.py DatasetBase).

    ``name`` namespaces any shared-filesystem state this dataset writes
    (InMemoryDataset's shuffle spool); QueueDataset accepts and ignores
    it (no shared state)."""

    def __init__(self, rank=None, world_size=None, name=None):
        if name is not None and (set(str(name)) & set("*?[]")
                                 or os.sep in str(name)
                                 or str(name).startswith(".")):
            # the name becomes a spool directory prefix AND a glob
            # pattern (reaping); separators would nest roots, glob
            # metachars would break cleanup forever
            raise ValueError(
                f"dataset name {name!r} must not contain path "
                f"separators, leading dots, or glob characters *?[]")
        self._name = name
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._parse_fn = None
        self._pipe_command = None
        self._use_vars = []
        self._rank, self._world = _resolve_rank_world(rank, world_size)

    # -- reference config setters ------------------------------------
    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_use_var(self, var_list):
        """Kept for API parity; the TPU pipeline feeds arrays positionally
        so the slot->Variable binding is a no-op here."""
        self._use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        """Reference semantics (data_feed.h pipe reader): each file's bytes
        are piped through this shell command; one output line = one
        record (before ``set_parse_fn`` post-processing)."""
        self._pipe_command = pipe_command

    def set_parse_fn(self, fn):
        """TPU-native extension replacing the protobuf DataFeedDesc: maps
        one raw text line -> one record object (any picklable value)."""
        self._parse_fn = fn

    # -- loading ------------------------------------------------------
    def _my_files(self):
        return self._filelist[self._rank::self._world]

    def _read_file(self, path):
        if self._pipe_command:
            with open(path, "rb") as f:
                out = subprocess.run(
                    self._pipe_command, shell=True, stdin=f,
                    capture_output=True, check=True)
            lines = out.stdout.decode("utf-8").splitlines()
        else:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.read().splitlines()
        parse = self._parse_fn or (lambda s: s)
        return [parse(ln) for ln in lines if ln]


class InMemoryDataset(_DatasetBase):
    """reference: data_set.h InMemoryDataset (global/local shuffle)."""

    def __init__(self, rank=None, world_size=None, name=None):
        super().__init__(rank, world_size, name=name)
        self._records = []
        self._loaded = False
        self._epoch = 0
        self._generation = 0  # per-instance global_shuffle call counter
        self._prev_ns = None  # namespace the PREVIOUS generation used

    def _spool_namespace(self) -> str:
        """Deterministic, SPMD-agreeing namespace isolating this
        dataset's spool files from other datasets sharing the same
        spool_dir: the explicit ``name=`` when given, else a fingerprint
        of the filelist (every rank sets the identical full filelist, so
        the hash agrees without coordination).  Two datasets with the
        SAME filelist sharing one spool_dir must be given distinct
        names."""
        if self._name:
            return str(self._name)
        h = hashlib.md5("\n".join(self._filelist).encode()).hexdigest()
        return f"ds{h[:8]}"

    # -- reference API -------------------------------------------------
    def load_into_memory(self):
        self._records = []
        for path in self._my_files():
            self._records.extend(self._read_file(path))
        self._loaded = True

    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    def local_shuffle(self, seed=None):
        seed = self._effective_seed(seed)
        # decorrelate ranks: same epoch seed must not give every worker
        # the same permutation pattern
        rs = np.random.RandomState((seed * 1000003 + self._rank)
                                   % (2 ** 31))
        order = rs.permutation(len(self._records))
        self._records = [self._records[i] for i in order]

    def global_shuffle(self, fleet=None, thread_num=None, seed=None,
                      spool_dir=None, timeout=120.0):
        """Seed-deterministic cross-worker shuffle; see module docstring.

        After this call each worker holds a disjoint contiguous chunk of
        the global permutation; the union over workers is the full
        dataset exactly once.  ``spool_dir`` must be a directory all
        workers can read/write (defaults to $PADDLE_TPU_SPOOL_DIR).
        ``fleet``/``thread_num`` are accepted for reference parity.
        """
        if not self._loaded:
            raise RuntimeError("call load_into_memory() before "
                               "global_shuffle() (reference semantics)")
        seed = self._effective_seed(seed)
        if self._world == 1:
            rs = np.random.RandomState(seed % (2 ** 31))
            order = rs.permutation(len(self._records))
            self._records = [self._records[i] for i in order]
            return

        spool_dir = spool_dir or os.environ.get("PADDLE_TPU_SPOOL_DIR")
        if not spool_dir:
            raise ValueError(
                "global_shuffle with world_size > 1 needs a shared "
                "spool_dir (arg or $PADDLE_TPU_SPOOL_DIR)")
        # generation counter in the root: every worker makes the same
        # sequence of global_shuffle calls (SPMD discipline), so the
        # counter agrees without coordination — and a repeated seed can
        # never satisfy the barriers with a previous call's sentinels.
        # Different jobs must still use distinct spool dirs.
        gen = self._generation
        self._generation += 1
        ns = self._spool_namespace()
        prev_ns, self._prev_ns = self._prev_ns, ns
        root = os.path.join(spool_dir, f"{ns}_gs_{gen}_{seed}")
        os.makedirs(root, exist_ok=True)

        # phase 1: publish local counts; derive global offsets
        n_local = len(self._records)
        with open(os.path.join(root, f"count_{self._rank}.json.tmp"),
                  "w") as f:
            json.dump(n_local, f)
        os.replace(os.path.join(root, f"count_{self._rank}.json.tmp"),
                   os.path.join(root, f"count_{self._rank}.json"))
        count_files = [os.path.join(root, f"count_{r}.json")
                       for r in range(self._world)]
        _wait_for(count_files, timeout, "record counts")
        counts = [json.load(open(p)) for p in count_files]
        total = sum(counts)
        my_off = sum(counts[:self._rank])

        # phase 2: identical global permutation + contiguous chunking
        rs = np.random.RandomState(seed % (2 ** 31))
        pi = rs.permutation(total)          # position p holds record pi[p]
        pos_of = np.argsort(pi)             # record g sits at position
        base, rem = divmod(total, self._world)
        starts = [r * base + min(r, rem) for r in range(self._world + 1)]

        def owner(pos):
            # inverse of the contiguous chunking above (first `rem`
            # ranks hold base+1 records; when base == 0 every position
            # falls in the first branch since hi == total)
            hi = (base + 1) * rem
            if pos < hi:
                return pos // (base + 1)
            return rem + (pos - hi) // base

        # phase 3: bucket my records by destination, spool, barrier
        outgoing = [[] for _ in range(self._world)]
        for i, rec in enumerate(self._records):
            g = my_off + i
            pos = int(pos_of[g])
            outgoing[owner(pos)].append((pos, rec))
        for t in range(self._world):
            tmp = os.path.join(root, f"data_{self._rank}_to_{t}.pkl.tmp")
            with open(tmp, "wb") as f:
                pickle.dump(outgoing[t], f)
            os.replace(tmp, os.path.join(root,
                                         f"data_{self._rank}_to_{t}.pkl"))

        # phase 4: gather my chunk, order by global position
        inbox = [os.path.join(root, f"data_{s}_to_{self._rank}.pkl")
                 for s in range(self._world)]
        _wait_for(inbox, timeout, "spooled shards")
        mine = []
        for p in inbox:
            with open(p, "rb") as f:
                mine.extend(pickle.load(f))
        mine.sort(key=lambda t: t[0])
        expect = starts[self._rank + 1] - starts[self._rank]
        if len(mine) != expect:  # protocol invariant, not data-dependent
            raise RuntimeError(
                f"global_shuffle: rank {self._rank} received {len(mine)} "
                f"records, expected {expect}")
        self._records = [rec for _, rec in mine]
        # done sentinel: proves this worker finished READING, which is
        # what makes the deferred cleanup below safe
        open(os.path.join(root, f"done_{self._rank}"), "w").close()
        self._reap_previous_generation(spool_dir, gen, prev_ns)

    def _reap_previous_generation(self, spool_dir, gen, prev_ns):
        """Delete generation ``gen - 1``'s spool once every worker's done
        sentinel proves no one still reads it (rank 0 only, best effort:
        a missing sentinel just defers cleanup).  ``prev_ns`` is the
        namespace that generation was WRITTEN under — set_filelist
        between shuffles changes the fingerprint, and reaping under the
        new one would orphan the old dirs."""
        if self._rank != 0 or gen == 0 or prev_ns is None:
            return
        prev = _glob.glob(os.path.join(
            spool_dir, f"{prev_ns}_gs_{gen - 1}_*"))
        for d in prev:
            if all(os.path.exists(os.path.join(d, f"done_{r}"))
                   for r in range(self._world)):
                try:
                    for f in _glob.glob(os.path.join(d, "*")):
                        os.unlink(f)
                    os.rmdir(d)
                except OSError:  # pragma: no cover - concurrent reap
                    pass

    def release_memory(self):
        self._records = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._records)

    def _effective_seed(self, seed):
        if seed is not None:
            return int(seed)
        # epoch-folded default: one reshuffle per epoch, same on every
        # worker (reference: fleet.global_shuffle called per epoch)
        return 9973 * self._epoch + 17

    # -- python dataset protocol (DataLoader interop) -----------------
    def __len__(self):
        return len(self._records)

    def __getitem__(self, idx):
        return self._records[idx]

    def __iter__(self):
        return iter(self._records)


class QueueDataset(_DatasetBase):
    """Streaming file-shard reader (reference: data_set.h QueueDataset —
    no global shuffle support, single pass)."""

    def global_shuffle(self, *a, **kw):
        raise RuntimeError("QueueDataset does not support global_shuffle "
                           "(reference parity: data_set.h QueueDataset)")

    def local_shuffle(self, *a, **kw):
        raise RuntimeError("QueueDataset does not support local_shuffle "
                           "(reference parity)")

    def __iter__(self):
        for path in self._my_files():
            yield from self._read_file(path)


class DatasetFactory:
    """reference: fluid/dataset.py DatasetFactory.create_dataset."""

    _KINDS = {"InMemoryDataset": InMemoryDataset,
              "QueueDataset": QueueDataset}

    def create_dataset(self, datafeed_class="QueueDataset", rank=None,
                       world_size=None, name=None):
        if datafeed_class not in self._KINDS:
            raise ValueError(
                f"unknown dataset class {datafeed_class!r}; expected one "
                f"of {sorted(self._KINDS)}")
        return self._KINDS[datafeed_class](rank=rank, world_size=world_size,
                                           name=name)


