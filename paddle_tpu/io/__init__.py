"""paddle_tpu.io — datasets and data loading.

Reference: python/paddle/io/ + fluid/reader.py (multi-process DataLoader with
shared-memory mmap tensors, reader.py:91-149) + fluid/dataloader/.

TPU-first design: the loader produces **host numpy batches** on background
threads and overlaps H2D transfer with compute via a device-prefetch queue
(double buffering) — the role the reference's py_reader/double-buffer
reader ops play (operators/reader/).  A C++ packing core (csrc/) accelerates
the hot batch-assembly path when built; pure-Python fallback otherwise.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..core.rng import default_generator
from ..core.tensor import Tensor


class Dataset:
    """Map-style dataset (reference: paddle/io/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t if isinstance(t, Tensor) else Tensor(t)
                        for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t.numpy()[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    assert sum(lengths) == n
    g = np.random.RandomState(default_generator().initial_seed or None)
    perm = g.permutation(n)
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


# -- samplers (reference: python/paddle/io/sampler.py, batch_sampler.py) ----

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        seed = default_generator().next_key()
        rs = np.random.RandomState(np.asarray(
            __import__("jax").random.key_data(seed))[-1] % (2 ** 31))
        if self.replacement:
            return iter(rs.randint(0, n, self.num_samples).tolist())
        return iter(rs.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rs = np.random.RandomState()
        idx = rs.choice(len(p), self.num_samples, replace=self.replacement,
                        p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        assert (dataset is None) != (sampler is None), \
            "exactly one of dataset/sampler"
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler).  On TPU with
    single-process SPMD, rank/nranks default to the mesh's dp axis."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rs = np.random.RandomState(self.epoch)
            indices = rs.permutation(n)
        # pad to make divisible, then take this rank's shard
        pad = self.total_size - n
        if pad > 0:
            indices = np.concatenate([indices, indices[:pad]])
        shard = indices[self.local_rank::self.nranks]
        batch = []
        for idx in shard.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# -- collation --------------------------------------------------------------

def default_collate_fn(batch: List[Any]):
    """Stack samples into batch arrays (reference:
    fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    raise TypeError(f"cannot collate type {type(sample)}")


def _emit_batch(batch, index: int):
    """Every DataLoader path funnels emitted batches through here — the
    ``dataloader.batch`` fault point.  An armed ``action=corrupt`` rule
    (testing/fault.py) poisons the emitted copy (nan/inf/bitflip) so
    chaos drills can prove the data-plane anomaly sentry catches a bad
    batch before it reaches the weights; disarmed, this is one bool
    check."""
    from ..testing import fault
    if fault.is_armed():
        batch = fault.corrupt_host("dataloader.batch", batch,
                                   f"batch={index}")
    return batch


class _PrefetchIterator:
    """Background-thread batch producer (double buffering).

    The reference gets overlap from C++ double-buffer reader ops
    (operators/reader/buffered_reader.cc); here a worker pool assembles
    numpy batches while TPU compute runs, and jax's async dispatch overlaps
    the H2D copy."""

    def __init__(self, loader, sampler_iter):
        self.loader = loader
        self.sampler_iter = sampler_iter
        self.q: queue.Queue = queue.Queue(maxsize=max(
            2, loader.prefetch_factor))
        self.done = object()
        self.threads = []
        n_workers = max(1, loader.num_workers)
        self.idx_q: queue.Queue = queue.Queue()
        self.out = {}
        self.next_emit = 0
        self.lock = threading.Lock()
        for i, idxs in enumerate(sampler_iter):
            self.idx_q.put((i, idxs))
        self.total = self.idx_q.qsize()
        for _ in range(n_workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self.threads.append(t)

    def _worker(self):
        while True:
            try:
                i, idxs = self.idx_q.get_nowait()
            except queue.Empty:
                return
            ds = self.loader.dataset
            samples = [ds[j] for j in idxs]
            collate = self.loader.collate_fn or default_collate_fn
            batch = collate(samples)
            self.q.put((i, batch))

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_emit >= self.total:
            raise StopIteration
        from ..core import flags as _flags
        timeout = (getattr(self.loader, "timeout", 0)
                   or _flags.get_flag("dataloader_timeout"))
        # emit in order
        while True:
            with self.lock:
                if self.next_emit in self.out:
                    b = self.out.pop(self.next_emit)
                    self.next_emit += 1
                    return _emit_batch(b, self.next_emit - 1)
            try:
                i, batch = self.q.get(timeout=timeout)
            except queue.Empty:
                raise RuntimeError(
                    f"DataLoader stalled: no batch for {timeout}s from "
                    f"the thread pool — raise DataLoader(timeout=...) or "
                    f"FLAGS_dataloader_timeout for slow datasets") \
                    from None
            with self.lock:
                self.out[i] = batch


class DataLoader:
    """reference: paddle.io.DataLoader (fluid/reader.py).

    num_workers>0 with use_shared_memory=True (default) runs real worker
    PROCESSES with shared-memory batch transport (io.multiprocess,
    reference fluid/reader.py:91-149) — the GIL-free path for Python-heavy
    transforms.  use_shared_memory=False falls back to the in-process
    thread pool (numpy releases the GIL for array collation).
    `places` accepted for API parity."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.return_list = return_list
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("DataLoader over IterableDataset has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if isinstance(self.dataset, IterableDataset):
            return self._iter_iterable()
        if self.batch_sampler is None:
            # no batching: sample-by-sample
            return (self.dataset[i] for i in range(len(self.dataset)))
        if self.num_workers > 0:
            if self.use_shared_memory:
                # real worker processes + shared-memory transport
                # (reference: fluid/reader.py:91-149); sidesteps the GIL
                # for Python-heavy transforms
                from .multiprocess import MultiprocessIterator
                return MultiprocessIterator(self, iter(self.batch_sampler))
            return _PrefetchIterator(self, iter(self.batch_sampler))
        return self._iter_sync()

    def _iter_sync(self):
        collate = self.collate_fn or default_collate_fn
        for i, idxs in enumerate(self.batch_sampler):
            yield _emit_batch(collate([self.dataset[j] for j in idxs]),
                              i)

    def _iter_iterable(self):
        collate = self.collate_fn or default_collate_fn
        batch = []
        i = 0
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _emit_batch(collate(batch), i)
                i += 1
                batch = []
        if batch and not self.drop_last:
            yield _emit_batch(collate(batch), i)

    def fetch_batch(self, i: int):
        """Assemble batch ``i`` of this (map-style, batch-sampled)
        loader on demand — the **re-delivery** path: after the anomaly
        sentry skips a corrupted delivery, or a quarantine advances
        past a blamed batch, the loop re-pulls through the same
        ``dataloader.batch`` fault/corruption point the iterators use,
        so a transient corruption clears on retry exactly like the
        worker batch-retry path.  Note a ``shuffle=True`` sampler is
        re-drawn per call; deterministic re-delivery wants
        ``shuffle=False`` or a fixed ``batch_sampler``."""
        if self.batch_sampler is None:
            raise TypeError("fetch_batch needs a map-style dataset "
                            "with a batch sampler")
        from itertools import islice
        idxs = next(islice(iter(self.batch_sampler), i, i + 1), None)
        if idxs is None:
            raise IndexError(f"fetch_batch({i}): the sampler yields "
                             f"fewer than {i + 1} batches")
        collate = self.collate_fn or default_collate_fn
        return _emit_batch(collate([self.dataset[j] for j in idxs]), i)


def get_worker_info():
    return None  # single-process loader: no worker context

# distributed dataset family (reference: fluid/dataset.py + data_set.h)
from .dataset_dist import (DatasetFactory, InMemoryDataset,  # noqa: F401,E402
                           QueueDataset)
