"""Multiprocess DataLoader workers with shared-memory transport.

Reference: fluid/reader.py:91-149 (_DataLoaderIterMultiProcess: worker
processes + mmap'd tensors + SIGCHLD cleanup) and
memory/allocation/mmap_allocator.cc.  The thread-pool path
(io.__init__._PrefetchIterator) is GIL-bound for Python-heavy
``__getitem__`` transforms; real processes sidestep the GIL, and batches
cross the process boundary through ``multiprocessing.shared_memory``
blocks (one memcpy in the worker, zero-copy numpy views in the parent)
instead of pickle.

Process model: ``forkserver`` by default — workers fork from a CLEAN
server interpreter, never from the training process (fork()-ing a parent
whose XLA/JAX runtime threads hold locks can deadlock the child; the
reference forks before CUDA init for the same reason).  The
dataset/collate_fn therefore must be picklable (module-level classes);
set ``PADDLE_TPU_MP_START=fork`` to opt into classic fork for
unpicklable datasets.  Workers are PERSISTENT: the pool is created at
the first epoch and reused by every subsequent iterator (torch's
persistent_workers semantics — it also means workers see the dataset as
pickled at pool creation; per-epoch dataset mutation does not propagate).
Workers run ``__getitem__`` + collation to NUMPY arrays only (no JAX in
children).  The parent re-assembles views, converts to device arrays,
and releases the block.  Worker death is detected on queue timeout (the
reference's SIGCHLD handler analog).  In-flight work is bounded to
``num_workers * prefetch_factor`` batches so /dev/shm never holds more
than the prefetch window."""
from __future__ import annotations

import atexit
import multiprocessing as mp
import os
import queue as queue_mod
from multiprocessing import shared_memory
from typing import Any, List

import numpy as np

from ..core.tensor import Tensor

_live_shm: set = set()


def _cleanup_shm():
    for name in list(_live_shm):
        try:
            s = shared_memory.SharedMemory(name=name)
            s.close()
            s.unlink()
        except Exception:
            pass


atexit.register(_cleanup_shm)


def _to_numpy(obj):
    """Tensor/array leaves -> numpy (workers must not ship device arrays)."""
    if isinstance(obj, Tensor):
        return np.asarray(obj.data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy(v) for k, v in obj.items()}
    return np.asarray(obj)


def _np_collate(batch):
    """Pure-numpy default collation (mirror of default_collate_fn minus
    the Tensor wrapping, which happens in the parent)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return np.asarray(batch)


def _flatten(tree, out):
    if isinstance(tree, np.ndarray):
        out.append(tree)
        return "*"
    if isinstance(tree, tuple):
        return tuple(_flatten(t, out) for t in tree)
    if isinstance(tree, list):
        return [_flatten(t, out) for t in tree]
    if isinstance(tree, dict):
        return {k: _flatten(v, out) for k, v in tree.items()}
    raise TypeError(f"cannot ship type {type(tree)} over shared memory")


def _unflatten(spec, leaves, it=None):
    if it is None:
        it = iter(leaves)
        return _unflatten(spec, leaves, it)
    if spec == "*":
        return next(it)
    if isinstance(spec, tuple):
        return tuple(_unflatten(s, leaves, it) for s in spec)
    if isinstance(spec, list):
        return [_unflatten(s, leaves, it) for s in spec]
    if isinstance(spec, dict):
        return {k: _unflatten(v, leaves, it) for k, v in spec.items()}
    raise TypeError(spec)


def _worker_loop(dataset, collate_fn, idx_q, result_q, worker_id,
                 worker_init_fn, seed):
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = idx_q.get()
        if item is None:
            return
        tag, i, idxs = item
        try:
            samples = [dataset[j] for j in idxs]
            batch = (_to_numpy(collate_fn(samples)) if collate_fn
                     else _np_collate([_to_numpy(s) for s in samples]))
            leaves: List[np.ndarray] = []
            spec = _flatten(batch, leaves)
            total = sum(a.nbytes for a in leaves)
            shm = shared_memory.SharedMemory(create=True,
                                             size=max(total, 1))
            # ownership passes to the parent (which unlinks after
            # tensorizing) — detach from this process's resource_tracker
            # so it doesn't warn about 'leaked' blocks at worker exit
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass
            metas, off = [], 0
            for a in leaves:
                shp = a.shape            # ascontiguousarray promotes 0-d
                a = np.ascontiguousarray(a)
                view = np.ndarray(a.shape, a.dtype, buffer=shm.buf,
                                  offset=off)
                view[...] = a
                metas.append((shp, a.dtype.str, off))
                off += a.nbytes
            shm.close()
            result_q.put((tag, i, shm.name, spec, metas, None))
        except Exception as e:  # surface the worker traceback in the parent
            import traceback
            result_q.put((tag, i, None, None, None,
                          f"{type(e).__name__}: {e}\n"
                          f"{traceback.format_exc()}"))


class _WorkerPool:
    """Persistent worker pool shared by successive epoch iterators."""

    def __init__(self, loader):
        method = os.environ.get("PADDLE_TPU_MP_START", "forkserver")
        if method not in mp.get_all_start_methods():
            method = "spawn"
        ctx = mp.get_context(method)
        self.idx_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.workers = []
        self.epoch = 0
        n = loader.num_workers
        for w in range(n):
            try:
                p = ctx.Process(
                    target=_worker_loop,
                    args=(loader.dataset, loader.collate_fn, self.idx_q,
                          self.result_q, w,
                          getattr(loader, "worker_init_fn", None),
                          int.from_bytes(os.urandom(4), "little")),
                    daemon=True)
                p.start()
            except Exception as e:
                self.close()
                raise RuntimeError(
                    f"DataLoader could not start a '{method}' worker "
                    f"({type(e).__name__}: {e}); a non-picklable dataset/"
                    f"collate_fn needs PADDLE_TPU_MP_START=fork or "
                    f"use_shared_memory=False") from e
            self.workers.append(p)

    def close(self):
        for p in self.workers:
            if p.is_alive():
                p.terminate()
        for p in self.workers:
            p.join(timeout=5)
        for q in (self.idx_q, self.result_q):
            while True:
                try:
                    item = q.get_nowait()
                except (queue_mod.Empty, OSError, ValueError):
                    break
                name = item[2] if len(item) >= 3 else None
                if isinstance(name, str):
                    try:
                        s = shared_memory.SharedMemory(name=name)
                        s.close()
                        s.unlink()
                    except Exception:
                        pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def get_pool(loader) -> _WorkerPool:
    pool = getattr(loader, "_mp_pool", None)
    if pool is None or not all(p.is_alive() for p in pool.workers):
        if pool is not None:
            pool.close()
        pool = _WorkerPool(loader)
        loader._mp_pool = pool
    return pool


class MultiprocessIterator:
    """Ordered batch producer over the loader's persistent pool."""

    def __init__(self, loader, sampler_iter):
        self.loader = loader
        self.pool = get_pool(loader)
        self.pool.epoch += 1
        self.tag = self.pool.epoch
        self.batches = list(sampler_iter)
        self.total = len(self.batches)
        self.pending = {}
        self.next_emit = 0
        self.timeout = getattr(loader, "timeout", 0) or 120
        # backpressure: at most num_workers * prefetch_factor batches in
        # flight, so /dev/shm holds a bounded window, not the whole epoch
        n = loader.num_workers
        self._window = max(
            n * max(int(getattr(loader, "prefetch_factor", 2)), 1), n)
        self._fed = 0
        while self._fed < min(self._window, self.total):
            self._feed_one()

    def _feed_one(self):
        if self._fed < self.total:
            self.pool.idx_q.put(
                (self.tag, self._fed, list(self.batches[self._fed])))
            self._fed += 1

    def __iter__(self):
        return self

    def _tensorize(self, shm_name, spec, metas):
        shm = shared_memory.SharedMemory(name=shm_name)
        _live_shm.add(shm_name)
        leaves = []
        for shape, dtype, off in metas:
            view = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf,
                              offset=off)
            # .copy() is required: jnp.asarray may zero-copy-alias a host
            # buffer, and the shm block is unlinked right below
            leaves.append(Tensor(view.copy()))
        shm.close()
        try:
            shm2 = shared_memory.SharedMemory(name=shm_name)
            shm2.close()
            shm2.unlink()
        except FileNotFoundError:
            pass
        _live_shm.discard(shm_name)
        return _unflatten(spec, leaves)

    def __next__(self):
        if self.next_emit >= self.total:
            raise StopIteration
        waited = 0.0
        while self.next_emit not in self.pending:
            try:
                tag, i, name, spec, metas, err = self.pool.result_q.get(
                    timeout=min(self.timeout, 15))
            except queue_mod.Empty:
                dead = [w for w, p in enumerate(self.pool.workers)
                        if not p.is_alive()]
                waited += min(self.timeout, 15)
                if not dead and waited < self.timeout:
                    continue          # alive but slow (loaded machine)
                self.pool.close()
                self.loader._mp_pool = None
                raise RuntimeError(
                    f"DataLoader worker(s) {dead or '?'} died or stalled "
                    f"(timeout={self.timeout}s) — reference analog: "
                    f"reader.py SIGCHLD handler.  If the dataset/collate "
                    f"is defined in a script's __main__, forkserver "
                    f"workers re-import the script (python spawn "
                    f"semantics): guard it with `if __name__ == "
                    f"'__main__'`, move the dataset to a module, or set "
                    f"PADDLE_TPU_MP_START=fork.")
            if tag != self.tag:
                # stale result from an abandoned earlier epoch: free it
                if name:
                    try:
                        s = shared_memory.SharedMemory(name=name)
                        s.close()
                        s.unlink()
                    except Exception:
                        pass
                continue
            if err is not None:
                self.pool.close()
                self.loader._mp_pool = None
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self.pending[i] = (name, spec, metas)
        name, spec, metas = self.pending.pop(self.next_emit)
        self.next_emit += 1
        self._feed_one()
        return self._tensorize(name, spec, metas)
