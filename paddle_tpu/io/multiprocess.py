"""Multiprocess DataLoader workers with shared-memory transport.

Reference: fluid/reader.py:91-149 (_DataLoaderIterMultiProcess: worker
processes + mmap'd tensors + SIGCHLD cleanup) and
memory/allocation/mmap_allocator.cc.  The thread-pool path
(io.__init__._PrefetchIterator) is GIL-bound for Python-heavy
``__getitem__`` transforms; real processes sidestep the GIL, and batches
cross the process boundary through ``multiprocessing.shared_memory``
blocks (one memcpy in the worker, zero-copy numpy views in the parent)
instead of pickle.

Process model: ``forkserver`` by default — workers fork from a CLEAN
server interpreter, never from the training process (fork()-ing a parent
whose XLA/JAX runtime threads hold locks can deadlock the child; the
reference forks before CUDA init for the same reason).  The
dataset/collate_fn therefore must be picklable (module-level classes);
set ``PADDLE_TPU_MP_START=fork`` to opt into classic fork for
unpicklable datasets.  Workers are PERSISTENT: the pool is created at
the first epoch and reused by every subsequent iterator (torch's
persistent_workers semantics — it also means workers see the dataset as
pickled at pool creation; per-epoch dataset mutation does not propagate).
Workers run ``__getitem__`` + collation to NUMPY arrays only (no JAX in
children).  The parent re-assembles views, converts to device arrays,
and releases the block.  Worker death is detected on queue timeout (the
reference's SIGCHLD handler analog) and is SELF-HEALING: dead workers
are respawned in place and their in-flight batches re-enqueued (bounded
by ``FLAGS_dataloader_batch_retries`` per batch), so a single OOM-killed
worker costs a recompute, not the epoch.  Deaths clustering inside
``FLAGS_dataloader_crashloop_window_s`` respawn with exponential
backoff, and past ``FLAGS_dataloader_crashloop_budget`` the loader
raises :class:`WorkerCrashLoop` (exit ledger attached) instead of
grinding the retry budget down in a tight loop.  Restart counts and exit codes
surface in ``monitor`` stats (``dataloader.worker_restarts``,
``dataloader.batch_retries``) and in the death diagnostic.  The stall
timeout honors ``DataLoader(timeout=...)`` end-to-end, defaulting to
``FLAGS_dataloader_timeout``.  Each worker owns private index/result
SimpleQueues (no cross-worker shared locks — a hard-killed worker can
wedge only its own pipes, which respawn replaces), and in-flight work
is bounded to ``prefetch_factor`` batches per worker so /dev/shm never
holds more than the prefetch window."""
from __future__ import annotations

import atexit
import multiprocessing as mp
import os
import time
from multiprocessing import shared_memory
from typing import List

import numpy as np

from ..core import flags as _flags
from ..core.tensor import Tensor
from ..testing import fault
from ..utils import monitor

_live_shm: set = set()


class WorkerCrashLoop(RuntimeError):
    """DataLoader workers are dying faster than respawning can help
    (``FLAGS_dataloader_crashloop_budget`` deaths inside
    ``FLAGS_dataloader_crashloop_window_s``).  Carries ``exit_history``
    — the (worker_id, exit_code) ledger — so the operator sees what
    kept dying (OOM kills show -9, native crashes show the signal)."""

    def __init__(self, msg: str, exit_history):
        super().__init__(msg)
        self.exit_history = list(exit_history)


def _cleanup_shm():
    for name in list(_live_shm):
        try:
            s = shared_memory.SharedMemory(name=name)
            s.close()
            s.unlink()
        except Exception:
            pass


atexit.register(_cleanup_shm)


def _free_shm(name):
    if not name:
        return
    try:
        s = shared_memory.SharedMemory(name=name)
        s.close()
        s.unlink()
    except Exception:
        pass


def _to_numpy(obj):
    """Tensor/array leaves -> numpy (workers must not ship device arrays)."""
    if isinstance(obj, Tensor):
        return np.asarray(obj.data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy(v) for k, v in obj.items()}
    return np.asarray(obj)


def _np_collate(batch):
    """Pure-numpy default collation (mirror of default_collate_fn minus
    the Tensor wrapping, which happens in the parent)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return np.asarray(batch)


def _flatten(tree, out):
    if isinstance(tree, np.ndarray):
        out.append(tree)
        return "*"
    if isinstance(tree, tuple):
        return tuple(_flatten(t, out) for t in tree)
    if isinstance(tree, list):
        return [_flatten(t, out) for t in tree]
    if isinstance(tree, dict):
        return {k: _flatten(v, out) for k, v in tree.items()}
    raise TypeError(f"cannot ship type {type(tree)} over shared memory")


def _unflatten(spec, leaves, it=None):
    if it is None:
        it = iter(leaves)
        return _unflatten(spec, leaves, it)
    if spec == "*":
        return next(it)
    if isinstance(spec, tuple):
        return tuple(_unflatten(s, leaves, it) for s in spec)
    if isinstance(spec, list):
        return [_unflatten(s, leaves, it) for s in spec]
    if isinstance(spec, dict):
        return {k: _unflatten(v, leaves, it) for k, v in spec.items()}
    raise TypeError(spec)


def _worker_loop(dataset, collate_fn, idx_q, result_q, worker_id,
                 worker_init_fn, seed, fault_spec=None):
    """Consume (tag, i, idxs) from this worker's PRIVATE idx_q, publish
    (tag, i, shm_name, spec, metas, err) on its PRIVATE result_q.

    The queues are SimpleQueues: ``put`` writes the pipe synchronously
    in this thread (no feeder), so once a result is put it SURVIVES any
    subsequent death of this process — and since no other worker shares
    these queues, dying mid-operation can wedge at most this worker's
    own pipes, which the parent replaces on respawn."""
    _dbg = None
    if os.environ.get("PADDLE_TPU_MP_DEBUG"):
        _dbg = open(f"/tmp/mpdbg.{worker_id}.{os.getpid()}", "a", 1)

    def _trace(msg):
        if _dbg:
            _dbg.write(msg + "\n")
    np.random.seed((seed + worker_id) % (2 ** 31))
    if fault_spec is not None:
        fault.arm(fault_spec[0], seed=fault_spec[1])
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    _trace("loop-start")
    while True:
        item = idx_q.get()
        if item is None:
            if _dbg:
                _dbg.close()
            return
        tag, i, idxs = item
        _trace(f"got {tag} {i}")
        # chaos hook: a rule like 'mp.worker_batch:count=1,action=exit,
        # match=batch=1' hard-kills one worker mid-epoch (the reference
        # SIGCHLD scenario)
        fault.point("mp.worker_batch", f"worker={worker_id}",
                    f"batch={i}")
        try:
            _trace(f"work {i}")
            samples = [dataset[j] for j in idxs]
            batch = (_to_numpy(collate_fn(samples)) if collate_fn
                     else _np_collate([_to_numpy(s) for s in samples]))
            leaves: List[np.ndarray] = []
            spec = _flatten(batch, leaves)
            total = sum(a.nbytes for a in leaves)
            _trace(f"shm-create {i}")
            shm = shared_memory.SharedMemory(create=True,
                                             size=max(total, 1))
            # ownership passes to the parent (which unlinks after
            # tensorizing) — detach from this process's resource_tracker
            # so it doesn't warn about 'leaked' blocks at worker exit
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass
            metas, off = [], 0
            for a in leaves:
                shp = a.shape            # ascontiguousarray promotes 0-d
                a = np.ascontiguousarray(a)
                view = np.ndarray(a.shape, a.dtype, buffer=shm.buf,
                                  offset=off)
                view[...] = a
                metas.append((shp, a.dtype.str, off))
                off += a.nbytes
            shm.close()
            _trace(f"put {i}")
            result_q.put((tag, i, shm.name, spec, metas, None))
            _trace(f"put-done {i}")
        except Exception as e:  # surface the worker traceback in the parent
            import traceback
            result_q.put((tag, i, None, None, None,
                          f"{type(e).__name__}: {e}\n"
                          f"{traceback.format_exc()}"))


class _WorkerPool:
    """Persistent worker pool shared by successive epoch iterators.

    Dead workers are respawned in place (``restart_worker``) — the
    reference tears the whole reader down from its SIGCHLD handler; a
    preemptible pod can't afford that, so the pool self-heals and keeps
    a ledger of restarts + exit codes for the diagnostics.

    Each worker owns PRIVATE index/result SimpleQueues (torch's
    _index_queues layout).  This is the crash-safety load-bearing wall:
    a shared queue's cross-process locks die with whoever holds them
    (a worker killed mid-``put`` on a shared result queue wedges every
    other worker forever), while a private queue can only wedge its
    owner — and ``restart_worker`` replaces the queues along with the
    process."""

    def __init__(self, loader):
        method = os.environ.get("PADDLE_TPU_MP_START", "forkserver")
        if method not in mp.get_all_start_methods():
            method = "spawn"
        self._ctx = mp.get_context(method)
        self._method = method
        self._loader = loader
        self._seed = int.from_bytes(os.urandom(4), "little")
        self.idx_qs: List = []
        self.res_qs: List = []
        self.workers: List = []
        self.epoch = 0
        self.restarts = 0
        self.exit_history: List[tuple] = []   # (worker_id, exit_code)
        self._death_times: List[float] = []   # crash-loop window ledger
        for w in range(loader.num_workers):
            try:
                self._spawn(w, respawn=False, replace=False)
            except Exception as e:
                self.close()
                raise RuntimeError(
                    f"DataLoader could not start a '{method}' worker "
                    f"({type(e).__name__}: {e}); a non-picklable dataset/"
                    f"collate_fn needs PADDLE_TPU_MP_START=fork or "
                    f"use_shared_memory=False") from e

    def _spawn(self, w, respawn, replace):
        loader = self._loader
        idx_q = self._ctx.SimpleQueue()
        res_q = self._ctx.SimpleQueue()
        p = self._ctx.Process(
            target=_worker_loop,
            args=(loader.dataset, loader.collate_fn, idx_q, res_q, w,
                  getattr(loader, "worker_init_fn", None), self._seed,
                  fault.spec_for_children(respawn=respawn)),
            daemon=True)
        p.start()
        # drop the parent's (unused) write end of the result pipe: once
        # the worker dies, reads hit EOF instead of blocking forever —
        # without this, a worker SIGKILLed mid-write of a result larger
        # than the pipe's atomic size would wedge drain_worker/close
        try:
            res_q._writer.close()
        except (OSError, AttributeError):
            pass
        if replace:
            self.idx_qs[w] = idx_q
            self.res_qs[w] = res_q
            self.workers[w] = p
        else:
            self.idx_qs.append(idx_q)
            self.res_qs.append(res_q)
            self.workers.append(p)

    def restart_worker(self, w) -> int:
        """Replace a dead worker — process AND queues (its pipes/locks
        may be wedged mid-operation); returns its exit code.

        Respawning is NOT free-running: deaths clustering inside
        ``FLAGS_dataloader_crashloop_window_s`` back off exponentially
        (first death respawns immediately — the common single-OOM case
        stays fast), and one death past
        ``FLAGS_dataloader_crashloop_budget`` raises
        :class:`WorkerCrashLoop` with the full exit ledger instead of
        burning ``FLAGS_dataloader_batch_retries`` in a tight loop."""
        dead = self.workers[w]
        dead.join(timeout=5)
        code = dead.exitcode
        self.exit_history.append((w, code))
        now = time.monotonic()
        window = float(_flags.get_flag("dataloader_crashloop_window_s"))
        self._death_times = [t for t in self._death_times
                             if now - t <= window] + [now]
        recent = len(self._death_times)
        budget = int(_flags.get_flag("dataloader_crashloop_budget"))
        if recent > budget:
            raise WorkerCrashLoop(
                f"DataLoader workers crash-looping: {recent} deaths "
                f"inside {window:.0f}s (budget {budget}).  Exit history "
                f"(worker, code): {self.exit_history} — repeated fast "
                f"deaths point at the dataset/collate_fn or a dying "
                f"node, not a transient fault; respawning harder "
                f"cannot fix it.", self.exit_history)
        if recent > 1:
            base = float(_flags.get_flag("dataloader_respawn_backoff_s"))
            cap = float(_flags.get_flag(
                "dataloader_respawn_backoff_max_s"))
            delay = min(base * (2 ** (recent - 2)), cap)
            monitor.stat_add("dataloader.respawn_backoff_s", delay)
            time.sleep(delay)
        self._spawn(w, respawn=True, replace=True)
        self.restarts += 1
        monitor.stat_add("dataloader.worker_restarts")
        from ..core import obs_hook
        trc = obs_hook._tracer
        if trc is not None:
            trc.emit("worker_restart", "dataloader.worker",
                     args={"worker": w, "exitcode": code,
                           "recent_deaths": recent})
        return code

    def drain_worker(self, w, handler):
        """Feed every already-readable result of worker ``w`` (the pipe
        contents survive the worker's death) to ``handler``; returns
        the number of messages handled (0 at EOF — dead worker)."""
        q = self.res_qs[w]
        n = 0
        while True:
            try:
                if not q._reader.poll():
                    return n
                msg = q.get()
            except (OSError, ValueError, EOFError):
                return n
            handler(w, msg)
            n += 1

    def close(self):
        for w, p in enumerate(self.workers):
            if p.is_alive():
                p.terminate()
        for p in self.workers:
            p.join(timeout=5)
        for q in self.res_qs:      # free shm of undelivered results
            while True:
                try:
                    if not q._reader.poll():
                        break
                    item = q.get()
                except (OSError, ValueError, EOFError):
                    break
                name = item[2] if len(item) >= 3 else None
                if isinstance(name, str):
                    _free_shm(name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def get_pool(loader) -> _WorkerPool:
    pool = getattr(loader, "_mp_pool", None)
    if pool is None or not all(p.is_alive() for p in pool.workers):
        if pool is not None:
            pool.close()
        pool = _WorkerPool(loader)
        loader._mp_pool = pool
    return pool


class MultiprocessIterator:
    """Ordered batch producer over the loader's persistent pool.

    The parent is the scheduler: it deals batches to each worker's
    private index queue (at most ``prefetch_factor`` in flight per
    worker) and tracks exactly what it dealt.  Worker results are
    synchronous pipe writes, so on a worker death the undelivered
    remainder of its deal — no more, no less — is re-dealt, and the
    retry budget is charged only to the batch the worker was actually
    chewing (the oldest undelivered one: workers run their queue in
    order)."""

    def __init__(self, loader, sampler_iter):
        from collections import deque
        self.loader = loader
        self.pool = get_pool(loader)
        self.pool.epoch += 1
        self.tag = self.pool.epoch
        self.batches = list(sampler_iter)
        self.total = len(self.batches)
        self.pending = {}
        self.next_emit = 0
        self.timeout = (getattr(loader, "timeout", 0)
                        or _flags.get_flag("dataloader_timeout"))
        self.retry_budget = int(
            _flags.get_flag("dataloader_batch_retries"))
        self.retries: dict = {}               # batch index -> re-deals
        # backpressure: at most prefetch_factor batches in flight per
        # worker, so /dev/shm holds a bounded window, not the whole epoch
        self._per_worker = max(
            int(getattr(loader, "prefetch_factor", 2)), 1)
        self.todo = deque(range(self.total))
        self.inflight = {w: deque()
                         for w in range(len(self.pool.workers))}
        self._fill()

    def _fill(self):
        """Deal todo batches to workers with free credit."""
        progress = True
        while self.todo and progress:
            progress = False
            for w, fl in self.inflight.items():
                if not self.todo:
                    break
                if len(fl) < self._per_worker:
                    i = self.todo.popleft()
                    self.pool.idx_qs[w].put(
                        (self.tag, i, list(self.batches[i])))
                    fl.append(i)
                    progress = True

    def _worker_status(self):
        return ", ".join(
            f"w{w}:{'alive' if p.is_alive() else p.exitcode}"
            for w, p in enumerate(self.pool.workers))

    def _ingest(self, w, msg):
        """Fold one result message from worker ``w`` into pending."""
        tag, i, name, spec, metas, err = msg
        if tag != self.tag:
            # stale result from an abandoned earlier epoch: free it
            _free_shm(name)
            return
        try:
            self.inflight[w].remove(i)
        except ValueError:
            pass
        if i < self.next_emit or i in self.pending:
            # duplicate of a re-dealt batch that survived after all:
            # every batch is emitted exactly once — drop it (even a
            # failed re-execution of an already-delivered batch)
            _free_shm(name)
            return
        if err is not None:
            self.pool.close()
            self.loader._mp_pool = None
            raise RuntimeError(f"DataLoader worker failed:\n{err}")
        self.pending[i] = (name, spec, metas)

    def _heal(self, dead):
        """Respawn dead workers (fresh queues) and re-deal exactly the
        batches they still owed.  Raises when a batch burns through its
        retry budget — a batch that kills every worker that touches it
        is a dataset bug, not a flaky node."""
        for w in dead:
            # the pipe outlives the process: collect results it
            # delivered before dying, so they aren't re-dealt
            self.pool.drain_worker(w, self._ingest)
            lost = list(self.inflight[w])
            self.inflight[w].clear()
            try:
                self.pool.restart_worker(w)
            except WorkerCrashLoop:
                # fast-fail: tear the pool down before surfacing, so
                # the crash loop doesn't leave zombie workers behind
                self.pool.close()
                self.loader._mp_pool = None
                raise
            if not lost:
                continue
            # workers run FIFO, so the oldest undelivered batch is the
            # one that was being processed at death: it takes the blame
            killer = lost[0]
            self.retries[killer] = self.retries.get(killer, 0) + 1
            if self.retries[killer] > self.retry_budget:
                self.pool.close()
                self.loader._mp_pool = None
                raise RuntimeError(
                    f"DataLoader batch(es) [{killer}] still failing "
                    f"after {self.retry_budget} worker-death retries "
                    f"(exit codes: {self.pool.exit_history}) — giving "
                    f"up.  A batch that repeatedly kills its worker "
                    f"points at the dataset/collate_fn (OOM, native "
                    f"crash), not a transient fault.")
            self.todo.extendleft(reversed(lost))
            monitor.stat_add("dataloader.batch_retries", len(lost))
        self._fill()

    def __iter__(self):
        return self

    def _tensorize(self, shm_name, spec, metas):
        shm = shared_memory.SharedMemory(name=shm_name)
        _live_shm.add(shm_name)
        leaves = []
        for shape, dtype, off in metas:
            view = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf,
                              offset=off)
            # .copy() is required: jnp.asarray may zero-copy-alias a host
            # buffer, and the shm block is unlinked right below
            leaves.append(Tensor(view.copy()))
        shm.close()
        try:
            shm2 = shared_memory.SharedMemory(name=shm_name)
            shm2.close()
            shm2.unlink()
        except FileNotFoundError:
            pass
        _live_shm.discard(shm_name)
        return _unflatten(spec, leaves)

    def __next__(self):
        from multiprocessing import connection as mp_conn
        if self.next_emit >= self.total:
            raise StopIteration
        poll = min(self.timeout, 2.0)
        waited = 0.0
        while self.next_emit not in self.pending:
            readers = {q._reader: w
                       for w, q in enumerate(self.pool.res_qs)}
            try:
                ready = mp_conn.wait(list(readers), timeout=poll)
            except OSError:
                ready = []
            handled = 0
            for r in ready:
                handled += self.pool.drain_worker(readers[r],
                                                  self._ingest)
            if handled:
                self._fill()
                waited = 0.0
                continue
            # nothing arrived: timed out, or a ready reader was a dead
            # worker's EOF'd pipe — check for deaths before looping so
            # an EOF'd pipe can't spin us without ever healing
            dead = [w for w, p in enumerate(self.pool.workers)
                    if not p.is_alive()]
            if dead:
                # self-heal: respawn + re-deal, then keep waiting
                self._heal(dead)
                waited = 0.0
                continue
            if ready:
                # momentary race (EOF visible, is_alive not yet False):
                # yield briefly; the next pass will see the death
                time.sleep(0.05)
                continue
            waited += poll
            if waited < self.timeout:
                continue              # alive but slow (loaded machine)
            self.pool.close()
            self.loader._mp_pool = None
            raise RuntimeError(
                f"DataLoader stalled: no batch for {self.timeout}s "
                f"with all workers alive ({self._worker_status()}; "
                f"restarts so far: {self.pool.exit_history or 'none'})"
                f" — raise DataLoader(timeout=...) or "
                f"FLAGS_dataloader_timeout for slow datasets.  If "
                f"the dataset/collate is defined in a script's "
                f"__main__, forkserver workers re-import the script "
                f"(python spawn semantics): guard it with `if "
                f"__name__ == '__main__'`, move the dataset to a "
                f"module, or set PADDLE_TPU_MP_START=fork.")
        name, spec, metas = self.pending.pop(self.next_emit)
        self.next_emit += 1
        self._fill()
        from . import _emit_batch
        return _emit_batch(self._tensorize(name, spec, metas),
                           self.next_emit - 1)
