"""Metrics (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing of (pred, label) before update."""
        return args


class Accuracy(Metric):
    """reference: metric/metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        res = []
        for k in self.topk:
            res.append(float(c[..., :k].sum()) / max(num, 1))
        self.total = [t + float(c[..., :k].sum())
                      for t, k in zip(self.total, self.topk)]
        self.count = [cnt + num for cnt in self.count]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram-bucketed ROC AUC (reference: metric/metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1)
        buckets = np.minimum(
            (p * self.num_thresholds).astype(np.int64), self.num_thresholds)
        for b, y in zip(buckets, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: metric.accuracy op)."""
    import jax.numpy as jnp
    from ..core.dispatch import apply

    def _acc(pred, lab):
        topk_idx = jnp.argsort(-pred, axis=-1)[..., :k]
        l = lab if lab.ndim == pred.ndim - 1 else lab.squeeze(-1)
        hit = jnp.any(topk_idx == l[..., None], axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply(_acc, input, label, op_name="accuracy", nondiff=True)
