"""paddle_tpu.utils — extension and observability utilities.

- :mod:`custom_op` — user custom-op registration (the reference's
  utils/cpp_extension C++ custom-op path, re-designed: a custom op is a
  pure jnp/pallas function, optionally with a custom VJP).
- :mod:`monitor` — process-wide stat gauges (reference:
  platform/monitor.h StatRegistry).
- :mod:`checkpoint` — auto-checkpointed epoch ranges (reference:
  incubate/checkpoint/auto_checkpoint.py train_epoch_range).
"""
from . import (checkpoint, cpp_extension, crypto, custom_op,  # noqa: F401
               fs, monitor, op_version)
from .checkpoint import train_epoch_range  # noqa: F401
from .custom_op import register_custom_op  # noqa: F401
