"""C++ custom-op extension path (compile + register native kernels).

Reference: ``python/paddle/utils/cpp_extension`` + the C++ registration
machinery in ``paddle/fluid/framework/custom_operator.cc`` and
``paddle/extension.h`` — users compile kernels against the framework ABI
and load them at runtime.

TPU-native redesign: the "framework ABI" is the **XLA FFI** (headers
shipped with jaxlib, ``jax.ffi.include_dir()``).  :func:`load` compiles
C++ sources declaring ``XLA_FFI_DEFINE_HANDLER_SYMBOL`` handlers into a
shared library, registers each exported handler as an XLA custom-call
target, and returns op callables built on ``jax.ffi.ffi_call`` — pure
jax functions that compose with jit/grad and can be wired through
:func:`paddle_tpu.utils.custom_op.register_custom_op` (including a
native backward as the custom-vjp pair).

Platform note (honest scope): FFI handlers are HOST kernels — they
register for the CPU platform.  Device-side custom kernels on TPU are
Pallas functions (`ops/pallas/`), which `register_custom_op` already
accepts as pure callables; there is no TPU device ABI for user C++ (the
reference's CUDA custom-op path has no TPU analog by construction).
"""
from __future__ import annotations

import ctypes
import os
import tempfile
from types import SimpleNamespace
from typing import Callable, Dict, Optional, Sequence, Union

import jax

from ..core.jax_compat import ffi as _ffi
import numpy as np

__all__ = ["load", "get_build_directory", "CppExtension"]

_OutSpec = Union[str, Callable, jax.ShapeDtypeStruct,
                 Sequence[jax.ShapeDtypeStruct]]


def get_build_directory() -> str:
    """reference: cpp_extension.get_build_directory (PADDLE_EXTENSION_DIR).
    Honors $PADDLE_TPU_EXTENSION_DIR, else a per-user temp dir."""
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"paddle_tpu_extensions_{os.getuid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _resolve_out(spec: _OutSpec, in_avals):
    if callable(spec) and not isinstance(spec, jax.ShapeDtypeStruct):
        return spec(*in_avals)
    if isinstance(spec, str):
        if not spec.startswith("like:"):
            raise ValueError(
                f"string out spec must be 'like:<input index>', got "
                f"{spec!r}")
        i = int(spec[5:])
        a = in_avals[i]
        return jax.ShapeDtypeStruct(a.shape, a.dtype)
    return spec


def _make_op(target: str, out: _OutSpec, vmap_method: Optional[str]):
    def op(*arrays, **attrs):
        avals = [jax.ShapeDtypeStruct(np.shape(a), a.dtype)
                 for a in arrays]
        out_aval = _resolve_out(out, avals)
        call = _ffi.ffi_call(target, out_aval, vmap_method=vmap_method)
        return call(*arrays, **attrs)

    op.__name__ = target.rsplit(".", 1)[-1]
    return op


def load(name: str, sources: Sequence[str],
         functions: Dict[str, dict],
         extra_cxx_cflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None,
         verbose: bool = False) -> SimpleNamespace:
    """Compile ``sources`` and register their FFI handlers as ops.

    reference: cpp_extension.load(name, sources, ...) — the JIT build
    path (setup()/CppExtension cover the ahead-of-time path).

    ``functions`` maps op name -> spec dict:
      - ``symbol``: the C symbol from XLA_FFI_DEFINE_HANDLER_SYMBOL
        (defaults to the op name);
      - ``out``: output aval — ``"like:<i>"`` (same shape/dtype as input
        i), a ``jax.ShapeDtypeStruct`` (or sequence for multi-output),
        or a callable ``(*in_avals) -> aval(s)``;
      - ``vmap_method``: forwarded to ``jax.ffi.ffi_call`` (default
        ``"sequential"`` so vmap works out of the box).

    Returns a namespace with one pure-jax callable per op, each usable
    directly, under jit/grad (via custom_vjp), or registered through
    ``register_custom_op``.
    """
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)

    srcs = [os.path.abspath(s) for s in sources]
    for s in srcs:
        if not os.path.exists(s):
            raise FileNotFoundError(f"cpp_extension.load: source {s}")
    # cache key = source CONTENTS + flags + FFI header identity: mtimes
    # lie (CI cache restores, tarballs), flag changes must rebuild, and
    # a jaxlib upgrade must not reuse a .so built against old headers
    import hashlib

    import jaxlib
    h = hashlib.sha1()
    h.update(getattr(jaxlib, "__version__", "?").encode())
    h.update(_ffi.include_dir().encode())
    for flag in (extra_cxx_cflags or []):
        h.update(flag.encode())
    for s in srcs:
        h.update(s.encode())
        with open(s, "rb") as f:
            h.update(f.read())
    so_path = os.path.join(build_dir,
                           f"lib{name}_{h.hexdigest()[:12]}.so")

    if not os.path.exists(so_path):
        from .native_build import build_shared_lib
        build_shared_lib(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
             f"-I{_ffi.include_dir()}"] + list(extra_cxx_cflags or []),
            srcs, so_path, verbose=verbose, what="cpp_extension.load")

    lib = ctypes.CDLL(so_path)
    ns = {}
    for op_name, spec in functions.items():
        symbol = spec.get("symbol", op_name)
        target = f"{name}.{op_name}"
        handler = getattr(lib, symbol)
        _ffi.register_ffi_target(
            target, _ffi.pycapsule(handler), platform="cpu")
        ns[op_name] = _make_op(target, spec["out"],
                               spec.get("vmap_method", "sequential"))
    module = SimpleNamespace(**ns)
    module.__so_path__ = so_path
    return module


class CppExtension:
    """reference: cpp_extension.CppExtension (setuptools AOT path).
    The JIT :func:`load` covers this environment; building wheels of
    custom ops is out of scope here, so constructing one raises with
    the supported alternative spelled out."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "CppExtension/setup(): ahead-of-time wheel builds are not "
            "supported in this build — use paddle_tpu.utils."
            "cpp_extension.load(name, sources, functions) to JIT-compile "
            "and register XLA FFI kernels")
