"""Shared native-library build helper.

One implementation of the compile-to-private-temp + atomic-rename dance
(a concurrent process must never dlopen a half-written .so), used by the
C-ABI predictor (inference/capi.py) and the cpp_extension loader."""
from __future__ import annotations

import os
import subprocess
from typing import Sequence

__all__ = ["build_shared_lib"]


def build_shared_lib(cmd_prefix: Sequence[str], sources: Sequence[str],
                     so_path: str, verbose: bool = False,
                     what: str = "native build") -> str:
    """Run ``cmd_prefix + sources + ['-o', <pid-unique tmp>]`` and
    atomically rename onto ``so_path``.  Raises RuntimeError with the
    compiler's stderr on failure."""
    tmp_path = f"{so_path}.{os.getpid()}.tmp"
    cmd = list(cmd_prefix) + list(sources) + ["-o", tmp_path]
    if verbose:
        print(f"{what}:", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        try:
            os.path.exists(tmp_path) and os.unlink(tmp_path)
        except OSError:  # pragma: no cover
            pass
        raise RuntimeError(f"{what}: compiler failed\n{proc.stderr}")
    os.replace(tmp_path, so_path)
    return so_path
