"""Model encryption for saved artifacts.

Reference: framework/io/crypto/ (cipher.h CipherBase, aes_cipher.cc —
AES encryption of inference models so weights at rest on shared storage
are unreadable; paddle_inference SetModelBuffer + decrypt-on-load).

TPU-native shape: authenticated AES-256-GCM over the serialized bytes
(the reference's AES-CBC + separate checksum, upgraded to an AEAD),
keyed by a user-provided key or a key file.  ``paddle.save(...,
encryption_key=...)`` / ``paddle.load(..., encryption_key=...)`` wrap
this transparently."""
from __future__ import annotations

import hashlib
import os

_MAGIC = b"PDTPUENC"


def _derive(key) -> bytes:
    if isinstance(key, str):
        key = key.encode("utf-8")
    return hashlib.sha256(key).digest()      # 32 bytes -> AES-256


def generate_key_file(path: str) -> bytes:
    """cipher.h CipherFactory/keygen parity: random 32-byte key file."""
    key = os.urandom(32)
    with open(path, "wb") as f:
        f.write(key)
    return key


def encrypt(data: bytes, key) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    k = _derive(key)
    nonce = os.urandom(12)
    ct = AESGCM(k).encrypt(nonce, data, _MAGIC)
    return _MAGIC + nonce + ct


def is_encrypted(head: bytes) -> bool:
    return head.startswith(_MAGIC)


def decrypt(blob: bytes, key) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    if not blob.startswith(_MAGIC):
        raise ValueError("not an encrypted paddle_tpu artifact")
    k = _derive(key)
    nonce, ct = blob[8:20], blob[20:]
    try:
        return AESGCM(k).decrypt(nonce, ct, _MAGIC)
    except Exception as e:
        raise ValueError(
            "decryption failed — wrong key or corrupted artifact") from e
