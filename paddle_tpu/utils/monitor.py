"""Process-wide stat gauges.

Reference: paddle/fluid/platform/monitor.h StatRegistry / STAT_ADD —
integer/float gauges keyed by name, readable for logging and tests."""
from __future__ import annotations

import threading
from typing import Dict, Union

__all__ = ["StatRegistry", "get_stat", "stat_add", "stat_set",
           "stat_reset", "all_stats"]


class StatRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Union[int, float]] = {}

    def add(self, name: str, v: Union[int, float] = 1):
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + v
            return self._stats[name]

    def set(self, name: str, v: Union[int, float]):
        with self._lock:
            self._stats[name] = v

    def get(self, name: str) -> Union[int, float]:
        with self._lock:
            return self._stats.get(name, 0)

    def reset(self, name: str = None):
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def snapshot(self) -> Dict[str, Union[int, float]]:
        with self._lock:
            return dict(self._stats)


_default = StatRegistry()


def get_stat(name):
    return _default.get(name)


def stat_add(name, v=1):
    return _default.add(name, v)


def stat_set(name, v):
    _default.set(name, v)


def stat_reset(name=None):
    _default.reset(name)


def all_stats():
    return _default.snapshot()
