"""Process-wide stat gauges and histograms.

Reference: paddle/fluid/platform/monitor.h StatRegistry / STAT_ADD —
integer/float gauges keyed by name, readable for logging and tests.
Histograms (``stat_observe`` / ``quantile``) extend the registry with
fixed log-spaced buckets for latency-style distributions; the serving
engine's p50/p95/p99 and ``/metrics`` endpoint are built on them.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Union

__all__ = ["StatRegistry", "get_stat", "stat_add", "stat_set",
           "stat_reset", "all_stats", "stat_observe", "quantile",
           "histogram_summary", "all_histograms", "histogram_raw",
           "quantile_from_counts"]

# Histogram bucket layout: log-spaced, 8 buckets per decade covering
# [1e-3, 1e7) — sub-microsecond to ~3 hours when observing milliseconds.
# Values outside the range clamp into the edge buckets; exact min/max/sum
# are tracked separately so the summary never lies about the extremes.
_H_LO_EXP = -3
_H_HI_EXP = 7
_H_PER_DECADE = 8
_H_NBUCKETS = (_H_HI_EXP - _H_LO_EXP) * _H_PER_DECADE


def _bucket_index(v: float) -> int:
    if v <= 10.0 ** _H_LO_EXP:
        return 0
    if v >= 10.0 ** _H_HI_EXP:
        return _H_NBUCKETS - 1
    return min(_H_NBUCKETS - 1,
               int((math.log10(v) - _H_LO_EXP) * _H_PER_DECADE))


def _bucket_bounds(i: int):
    lo = 10.0 ** (_H_LO_EXP + i / _H_PER_DECADE)
    hi = 10.0 ** (_H_LO_EXP + (i + 1) / _H_PER_DECADE)
    return lo, hi


class _Histogram:
    __slots__ = ("counts", "n", "total", "vmin", "vmax")

    def __init__(self):
        self.counts: List[int] = [0] * _H_NBUCKETS
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, v: float):
        self.counts[_bucket_index(v)] += 1
        self.n += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1) from the buckets.

        Interpolates linearly by rank within the bucket holding the
        rank ``q*n``: a rank at the bucket's first sample reads the
        bucket's lower edge, at its last the upper edge — exact at
        bucket edges and exact for uniformly-spread samples, where the
        old geometric-midpoint estimate carried a fixed ~15%
        bucket-resolution error regardless of where the rank fell.
        Estimates clamp to the exactly-tracked [min, max], so p0/p100
        are always exact and a single-valued bucket reads exactly."""
        if self.n == 0:
            return 0.0
        if q <= 0.0:
            return self.vmin
        if q >= 1.0:
            return self.vmax
        rank = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            prev, seen = seen, seen + c
            if c and seen >= rank:
                lo, hi = _bucket_bounds(i)
                est = lo + (hi - lo) * ((rank - prev) / c)
                return min(max(est, self.vmin), self.vmax)
        return self.vmax

    def raw(self) -> Dict[str, object]:
        """Cumulative bucket counts + exact aggregates — the windowable
        view: two raws taken at different times subtract bucket-wise
        into the distribution of the interval between them (the SLO
        monitor's rolling-window quantiles are built on this)."""
        return {"counts": tuple(self.counts), "count": self.n,
                "sum": self.total,
                "min": self.vmin if self.n else 0.0,
                "max": self.vmax if self.n else 0.0}

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.n,
            "sum": self.total,
            "mean": (self.total / self.n) if self.n else 0.0,
            "min": self.vmin if self.n else 0.0,
            "max": self.vmax if self.n else 0.0,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


class StatRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, Union[int, float]] = {}
        self._hists: Dict[str, _Histogram] = {}

    def add(self, name: str, v: Union[int, float] = 1):
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + v
            return self._stats[name]

    def set(self, name: str, v: Union[int, float]):
        with self._lock:
            self._stats[name] = v

    def get(self, name: str) -> Union[int, float]:
        with self._lock:
            return self._stats.get(name, 0)

    def reset(self, name: str = None):
        with self._lock:
            if name is None:
                self._stats.clear()
                self._hists.clear()
            else:
                self._stats.pop(name, None)
                self._hists.pop(name, None)

    def snapshot(self) -> Dict[str, Union[int, float]]:
        with self._lock:
            return dict(self._stats)

    # -- histograms -------------------------------------------------------
    def observe(self, name: str, v: float):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.observe(float(v))

    def quantile(self, name: str, q: float) -> float:
        with self._lock:
            h = self._hists.get(name)
            return h.quantile(q) if h is not None else 0.0

    def histogram_summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            h = self._hists.get(name)
            return h.summary() if h is not None else _Histogram().summary()

    def histogram_raw(self, name: str):
        """Cumulative bucket counts for ``name`` (None if unobserved)."""
        with self._lock:
            h = self._hists.get(name)
            return h.raw() if h is not None else None

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: h.summary() for k, h in self._hists.items()}


_default = StatRegistry()


def get_stat(name):
    return _default.get(name)


def stat_add(name, v=1):
    return _default.add(name, v)


def stat_set(name, v):
    _default.set(name, v)


def stat_reset(name=None):
    _default.reset(name)


def all_stats():
    return _default.snapshot()


def stat_observe(name, v):
    """Record one sample into the log-bucketed histogram ``name``."""
    _default.observe(name, v)


def quantile(name, q):
    """Estimated q-quantile of histogram ``name`` (0.0 if unobserved)."""
    return _default.quantile(name, q)


def histogram_summary(name):
    """count/sum/mean/min/max/p50/p95/p99 for histogram ``name``."""
    return _default.histogram_summary(name)


def all_histograms():
    return _default.histograms()


def histogram_raw(name):
    """Cumulative bucket counts/aggregates for histogram ``name``
    (None if it was never observed) — the subtractable view rolling
    windows are computed from."""
    return _default.histogram_raw(name)


def quantile_from_counts(counts, n: int, q: float,
                         vmin=None, vmax=None) -> float:
    """q-quantile of a raw bucket-count vector (e.g. the difference of
    two :func:`histogram_raw` snapshots).  Same rank-linear
    interpolation as :meth:`_Histogram.quantile`; a windowed delta has
    no per-window extremes, but the histogram's CUMULATIVE min/max
    (``raw()['min']/['max']``) always bound any window's values —
    pass them as ``vmin``/``vmax`` so the estimate can't overshoot
    the true extreme by a bucket width (an un-clamped p99 can read up
    to ~1.33x the largest value ever observed and falsely breach an
    SLO the service is actually inside)."""
    if n <= 0:
        return 0.0
    rank = min(max(q, 0.0), 1.0) * n
    seen = 0
    last = 0
    est = None
    for i, c in enumerate(counts):
        if not c:
            continue
        last = i
        prev, seen = seen, seen + c
        if seen >= rank:
            lo, hi = _bucket_bounds(i)
            est = lo + (hi - lo) * (max(rank - prev, 0.0) / c)
            break
    if est is None:
        est = _bucket_bounds(last)[1]
    if vmax is not None:
        est = min(est, vmax)
    if vmin is not None:
        est = max(est, vmin)
    return est
