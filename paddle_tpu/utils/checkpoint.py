"""Auto-checkpointed training ranges.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range:71, TrainEpochRange save/restore:265) — epoch loops
that snapshot registered state and resume transparently after a restart.

TPU-native: state is whatever exposes ``state_dict``/``set_state_dict``
(Layers, optimizers, GradScalers, LR schedules); snapshots go through
``paddle_tpu.save`` (npz pytrees) plus a small json meta, written
atomically (tmp + rename) so a preemption mid-save can't corrupt the
latest checkpoint.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterator, Optional

from ..framework_io import load as _load
from ..framework_io import save as _save

__all__ = ["TrainEpochRange", "train_epoch_range"]


class TrainEpochRange:
    """Iterable of epoch indices with save-on-epoch-end and auto-resume.

    Usage::

        r = TrainEpochRange(10, "ckpt/run1", model=model, opt=opt)
        for epoch in r:          # resumes after the last finished epoch
            train_one_epoch(...)
    """

    def __init__(self, max_epoch_num: int, checkpoint_dir: str,
                 save_checkpoint_inter: int = 1, **objects):
        self.max_epoch = int(max_epoch_num)
        self.dir = checkpoint_dir
        self.interval = max(1, int(save_checkpoint_inter))
        self._objects: Dict[str, object] = dict(objects)
        os.makedirs(self.dir, exist_ok=True)

    def register(self, name: str, obj):
        """Add a state_dict-bearing object to the snapshot set."""
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _meta_path(self):
        return os.path.join(self.dir, "range_meta.json")

    def _load_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _save(self, epoch: int):
        # stage the WHOLE snapshot in an epoch directory, then publish it
        # atomically through the meta: a preemption at any point leaves
        # either the previous complete snapshot or the new complete one —
        # never a mixed-epoch state
        snap = f"epoch_{epoch}"
        sdir = os.path.join(self.dir, snap)
        os.makedirs(sdir, exist_ok=True)
        for name, obj in self._objects.items():
            _save(obj.state_dict(), os.path.join(sdir, f"{name}.pdparams"))
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"finished_epoch": epoch, "snapshot": snap,
                       "objects": sorted(self._objects)}, f)
        os.replace(tmp, self._meta_path())  # atomic publish
        # prune superseded snapshots
        import shutil
        for d in os.listdir(self.dir):
            if d.startswith("epoch_") and d != snap:
                shutil.rmtree(os.path.join(self.dir, d),
                              ignore_errors=True)

    def _restore(self) -> int:
        meta = self._load_meta()
        if meta is None:
            return 0
        sdir = os.path.join(self.dir, meta.get("snapshot", ""))
        for name, obj in self._objects.items():
            path = os.path.join(sdir, f"{name}.pdparams")
            if os.path.exists(path):
                obj.set_state_dict(_load(path))
        return int(meta.get("finished_epoch", -1)) + 1

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self._restore()
        for epoch in range(start, self.max_epoch):
            yield epoch
            # body finished without raising: snapshot this epoch
            if (epoch + 1) % self.interval == 0 or epoch == self.max_epoch - 1:
                self._save(epoch)

    @property
    def next_epoch(self) -> int:
        meta = self._load_meta()
        return 0 if meta is None else int(meta["finished_epoch"]) + 1


def train_epoch_range(max_epoch_num: int, checkpoint_dir: str = "./acp",
                      save_checkpoint_inter: int = 1,
                      **objects) -> TrainEpochRange:
    """reference: auto_checkpoint.py train_epoch_range:71."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir,
                           save_checkpoint_inter, **objects)
