"""Auto-checkpointed training ranges.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range:71, TrainEpochRange save/restore:265) — epoch loops
that snapshot registered state and resume transparently after a restart.

TPU-native: state is whatever exposes ``state_dict``/``set_state_dict``
(Layers, optimizers, GradScalers, LR schedules); snapshots go through
``paddle_tpu.save`` (npz pytrees) plus a small json meta, written
atomically (tmp + rename) so a preemption mid-save can't corrupt the
latest checkpoint.  ``checkpoint_dir`` may carry a registered filesystem
scheme (``hdfs://...`` — utils/fs.py, reference framework/io/fs.cc), so
fleet preemption recovery can land on a remote store.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterator, Optional

from . import fs as _fsmod
from ..framework_io import load as _load
from ..framework_io import save as _save

__all__ = ["TrainEpochRange", "train_epoch_range"]


class TrainEpochRange:
    """Iterable of epoch indices with save-on-epoch-end and auto-resume.

    Usage::

        r = TrainEpochRange(10, "ckpt/run1", model=model, opt=opt)
        for epoch in r:          # resumes after the last finished epoch
            train_one_epoch(...)
    """

    def __init__(self, max_epoch_num: int, checkpoint_dir: str,
                 save_checkpoint_inter: int = 1, **objects):
        self.max_epoch = int(max_epoch_num)
        self.dir = checkpoint_dir
        self.interval = max(1, int(save_checkpoint_inter))
        self._objects: Dict[str, object] = dict(objects)
        self._fs = _fsmod.get_fs(checkpoint_dir)
        self._fs.mkdir(self.dir)

    def register(self, name: str, obj):
        """Add a state_dict-bearing object to the snapshot set."""
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _join(self, *parts):
        return "/".join([self.dir.rstrip("/")] + list(parts))

    def _meta_path(self):
        return self._join("range_meta.json")

    def _load_meta(self) -> Optional[dict]:
        try:
            with self._fs.open_read(self._meta_path()) as f:
                return json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, RuntimeError):
            return None

    def _save(self, epoch: int):
        # stage the WHOLE snapshot in an epoch directory, then publish it
        # atomically through the meta: a preemption at any point leaves
        # either the previous complete snapshot or the new complete one —
        # never a mixed-epoch state
        snap = f"epoch_{epoch}"
        sdir = self._join(snap)
        self._fs.mkdir(sdir)
        for name, obj in self._objects.items():
            _save(obj.state_dict(), f"{sdir}/{name}.pdparams")
        tmp = self._meta_path() + ".tmp"
        with self._fs.open_write(tmp) as f:
            f.write(json.dumps(
                {"finished_epoch": epoch, "snapshot": snap,
                 "objects": sorted(self._objects)}).encode("utf-8"))
        self._fs.mv(tmp, self._meta_path())  # atomic publish
        # prune superseded snapshots
        for d in self._fs.list(self.dir):
            if d.startswith("epoch_") and d != snap:
                try:
                    self._fs.remove(self._join(d))
                except (RuntimeError, OSError):
                    pass  # prune is best-effort (shared dirs, perms)

    def _restore(self) -> int:
        meta = self._load_meta()
        if meta is None:
            return 0
        sdir = self._join(meta.get("snapshot", ""))
        for name, obj in self._objects.items():
            path = f"{sdir}/{name}.pdparams"
            if self._fs.exists(path):
                obj.set_state_dict(_load(path))
        return int(meta.get("finished_epoch", -1)) + 1

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self._restore()
        for epoch in range(start, self.max_epoch):
            yield epoch
            # body finished without raising: snapshot this epoch
            if (epoch + 1) % self.interval == 0 or epoch == self.max_epoch - 1:
                self._save(epoch)

    @property
    def next_epoch(self) -> int:
        meta = self._load_meta()
        return 0 if meta is None else int(meta["finished_epoch"]) + 1


def train_epoch_range(max_epoch_num: int, checkpoint_dir: str = "./acp",
                      save_checkpoint_inter: int = 1,
                      **objects) -> TrainEpochRange:
    """reference: auto_checkpoint.py train_epoch_range:71."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir,
                           save_checkpoint_inter, **objects)
