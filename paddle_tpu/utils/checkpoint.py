"""Auto-checkpointed training ranges.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range:71, TrainEpochRange save/restore:265) — epoch loops
that snapshot registered state and resume transparently after a restart.

TPU-native: state is whatever exposes ``state_dict``/``set_state_dict``
(Layers, optimizers, GradScalers, LR schedules); snapshots go through
``paddle_tpu.save`` (npz pytrees) plus a small json meta, written
atomically (tmp + rename) so a preemption mid-save can't corrupt the
latest checkpoint.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterator, Optional

from ..framework_io import load as _load
from ..framework_io import save as _save

__all__ = ["TrainEpochRange", "train_epoch_range"]


class TrainEpochRange:
    """Iterable of epoch indices with save-on-epoch-end and auto-resume.

    Usage::

        r = TrainEpochRange(10, "ckpt/run1", model=model, opt=opt)
        for epoch in r:          # resumes after the last finished epoch
            train_one_epoch(...)
    """

    def __init__(self, max_epoch_num: int, checkpoint_dir: str,
                 save_checkpoint_inter: int = 1, **objects):
        self.max_epoch = int(max_epoch_num)
        self.dir = checkpoint_dir
        self.interval = max(1, int(save_checkpoint_inter))
        self._objects: Dict[str, object] = dict(objects)
        os.makedirs(self.dir, exist_ok=True)

    def register(self, name: str, obj):
        """Add a state_dict-bearing object to the snapshot set."""
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _meta_path(self):
        return os.path.join(self.dir, "range_meta.json")

    def _state_path(self, name):
        return os.path.join(self.dir, f"{name}.pdparams")

    def _load_meta(self) -> Optional[dict]:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _save(self, epoch: int):
        for name, obj in self._objects.items():
            tmp = self._state_path(name) + ".tmp"
            _save(obj.state_dict(), tmp)
            os.replace(tmp, self._state_path(name))  # atomic per file
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"finished_epoch": epoch,
                       "objects": sorted(self._objects)}, f)
        os.replace(tmp, self._meta_path())  # atomic publish

    def _restore(self) -> int:
        meta = self._load_meta()
        if meta is None:
            return 0
        for name, obj in self._objects.items():
            path = self._state_path(name)
            if os.path.exists(path):
                obj.set_state_dict(_load(path))
        return int(meta.get("finished_epoch", -1)) + 1

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self._restore()
        for epoch in range(start, self.max_epoch):
            yield epoch
            # body finished without raising: snapshot this epoch
            if (epoch + 1) % self.interval == 0 or epoch == self.max_epoch - 1:
                self._save(epoch)

    @property
    def next_epoch(self) -> int:
        meta = self._load_meta()
        return 0 if meta is None else int(meta["finished_epoch"]) + 1


def train_epoch_range(max_epoch_num: int, checkpoint_dir: str = "./acp",
                      save_checkpoint_inter: int = 1,
                      **objects) -> TrainEpochRange:
    """reference: auto_checkpoint.py train_epoch_range:71."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir,
                           save_checkpoint_inter, **objects)
