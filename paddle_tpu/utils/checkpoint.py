"""Auto-checkpointed training ranges.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range:71, TrainEpochRange save/restore:265) — epoch loops
that snapshot registered state and resume transparently after a restart.

TPU-native: state is whatever exposes ``state_dict``/``set_state_dict``
(Layers, optimizers, GradScalers, LR schedules); snapshots go through
``paddle_tpu.save`` (npz pytrees) plus a small json meta, written
atomically (tmp + rename) so a preemption mid-save can't corrupt the
latest checkpoint.  ``checkpoint_dir`` may carry a registered filesystem
scheme (``hdfs://...`` — utils/fs.py, reference framework/io/fs.cc), so
fleet preemption recovery can land on a remote store.

Integrity tier: every snapshot file's sha256 lands in the published
meta and is re-verified on restore — a corrupt or missing file NEVER
part-loads; restore falls back to the previous intact snapshot (the
meta keeps the last ``keep_checkpoint_max``) or raises
:class:`CheckpointError` loudly.  A SIGTERM (the TPU-pod preemption
notice) requests a save at the next epoch boundary, publishes it, and
exits cleanly — ``tools/chaos_smoke.py`` proves the round trip.
Recovery events surface in ``monitor`` stats (``checkpoint.saves``,
``checkpoint.fallbacks``, ``checkpoint.preempt_saves``).

Step-cadence tier (this is what makes supervised restarts cheap enough
to be routine — ``distributed/supervisor.py``): ``TrainEpochRange``
grows ``save_every_steps`` / ``save_every_s``; the training loop calls
:meth:`TrainEpochRange.step` once per step, and due snapshots are
*captured* on the step thread (state serialization — consistent even
under the donated Executor, whose buffers the next step invalidates)
but *published* (digests, atomic writes, meta) on a background thread,
so the step loop never waits on the checkpoint store.  With a cadence
configured, SIGTERM saves at the next **step** boundary, not epoch.
Step snapshots ride the same digest-verified meta (``kind: "step"``);
restore resumes mid-epoch and reports ``resume_step``.
"""
from __future__ import annotations

import hashlib
import json
import signal
import threading
import time
import warnings
from typing import Dict, Iterator, List, Optional, Sequence

from . import fs as _fsmod
from . import monitor
from ..core import flags as _flags
from ..core import obs_hook as _obs_hook
from ..framework_io import dumps as _dumps
from ..framework_io import loads as _loads
from ..testing import fault

__all__ = ["CheckpointError", "SnapshotStore", "TrainEpochRange",
           "install_preemption_handler", "train_epoch_range"]


class CheckpointError(RuntimeError):
    """No intact snapshot could be restored (corrupt/missing state)."""


def install_preemption_handler(on_term):
    """Install a SIGTERM handler that calls ``on_term()`` then chains to
    the previous Python handler.  Returns a ``restore()`` callable, or
    None when installation isn't possible (non-main thread, or the
    previous handler was installed by non-Python code — ``getsignal``
    returns None — which we could neither chain nor restore)."""
    if threading.current_thread() is not threading.main_thread():
        return None
    prev = signal.getsignal(signal.SIGTERM)
    if prev is None:
        return None

    def _handler(signum, frame):
        on_term()
        # chain: give outer handlers (fleet agents) their notice too
        if callable(prev) and prev not in (signal.SIG_IGN,
                                           signal.SIG_DFL):
            prev(signum, frame)

    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:      # non-main interpreter thread raced us
        return None

    def restore():
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, TypeError):
            pass

    return restore


class SnapshotStore:
    """Versioned, digest-verified snapshot directory.

    Layout: ``<dir>/epoch_<n>/<name>.pdparams`` per registered object,
    published atomically through ``<dir>/range_meta.json`` whose
    ``snapshots`` list carries per-file sha256 digests.  Keeps the last
    ``keep_max`` snapshots so a corrupt latest can fall back."""

    META = "range_meta.json"

    def __init__(self, directory: str, keep_max: Optional[int] = None,
                 verify: bool = True):
        self.dir = directory
        self.keep_max = max(1, int(
            keep_max if keep_max is not None
            else _flags.get_flag("checkpoint_keep_max")))
        self.verify = verify
        self._fs = _fsmod.get_fs(directory)
        self._fs.mkdir(directory)
        # the snapshot applied by the last restore() (meta entry dict),
        # or None — step-cadence resume reads its "step" from here
        self.last_restored: Optional[dict] = None
        # background publisher (save_async): captured payloads queue
        # here; ONE thread does digests + atomic writes + meta publish,
        # so publish order — and therefore meta monotonicity — is the
        # enqueue order
        self._pub_cv = threading.Condition()
        self._pub_queue = None
        self._pub_thread: Optional[threading.Thread] = None
        self._pub_pending = 0
        self._pub_error: Optional[BaseException] = None

    def _join(self, *parts) -> str:
        return "/".join([self.dir.rstrip("/")] + list(parts))

    def _meta_path(self) -> str:
        return self._join(self.META)

    def load_meta(self) -> Optional[dict]:
        try:
            with self._fs.open_read(self._meta_path()) as f:
                meta = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, RuntimeError):
            return None
        # v1 metas (pre-digest) carried only the latest snapshot
        if "snapshots" not in meta:
            meta["snapshots"] = [{"epoch": int(meta.get(
                "finished_epoch", -1)), "dir": meta.get("snapshot", ""),
                "digests": None}]
        return meta

    # -- save --------------------------------------------------------------
    def _encode(self, objects: Dict[str, object]) -> Dict[str, bytes]:
        """Capture every object's state as bytes — the *consistency*
        half of a save.  Runs on the caller's thread: under the donated
        Executor a later step invalidates the buffers a state_dict
        refers to, so the capture cannot be deferred (the publish
        can)."""
        files: Dict[str, bytes] = {}
        for name, obj in objects.items():
            if hasattr(obj, "shard_state"):
                # sharded protocol (distributed/sharding.ShardedState):
                # one payload per unique shard + a manifest naming them
                # — every file gets its own digest, so a single corrupt
                # shard is caught without touching the others
                manifest, payloads = obj.shard_state()
                files[f"{name}.manifest.json"] = json.dumps(
                    manifest).encode("utf-8")
                for fname, data in payloads.items():
                    files[f"{name}.{fname}"] = data
                continue
            files[f"{name}.pdparams"] = _dumps(obj.state_dict())
        return files

    def _publish(self, epoch: int, files: Dict[str, bytes],
                 object_names: List[str], step: Optional[int] = None,
                 kind: str = "epoch") -> None:
        """Write payloads + digests and atomically publish the meta —
        the *durability* half of a save."""
        snap = f"step_{step}" if kind == "step" else f"epoch_{epoch}"
        sdir = self._join(snap)
        self._fs.mkdir(sdir)
        digests = {}
        for fname, data in files.items():
            digests[fname] = hashlib.sha256(data).hexdigest()
            _fsmod.write_atomic(f"{sdir}/{fname}", data)
        meta = self.load_meta() or {"snapshots": []}
        snaps = [s for s in meta["snapshots"] if s.get("dir") != snap]
        entry = {"epoch": int(epoch), "dir": snap, "digests": digests,
                 "kind": kind}
        if step is not None:
            entry["step"] = int(step)
        snaps.append(entry)
        snaps = snaps[-self.keep_max:]
        # a step snapshot mid-epoch E means E is NOT finished
        finished = int(epoch) if kind == "epoch" else int(epoch) - 1
        meta = {"finished_epoch": finished, "snapshot": snap,
                "objects": sorted(object_names), "snapshots": snaps}
        fault.point("ckpt.publish", self.dir, epoch)
        _fsmod.write_atomic(self._meta_path(),
                            json.dumps(meta).encode("utf-8"))
        monitor.stat_add("checkpoint.saves")
        trc = _obs_hook._tracer
        if trc is not None:
            trc.emit("checkpoint", "save",
                     args={"epoch": int(epoch), "step": step,
                           "kind": kind, "dir": self.dir})
        keep = {s["dir"] for s in snaps}
        for d in self._fs.list(self.dir):
            if (d.startswith("epoch_") or d.startswith("step_")) \
                    and d not in keep:
                try:
                    self._fs.remove(self._join(d))
                except (RuntimeError, OSError):
                    pass  # prune is best-effort (shared dirs, perms)

    def save(self, epoch: int, objects: Dict[str, object],
             step: Optional[int] = None, kind: str = "epoch") -> None:
        """Synchronous save: capture + publish on this thread.  Flushes
        any queued background publishes first so the meta never moves
        backwards past an already-captured snapshot."""
        fault.point("ckpt.save", self.dir, epoch)
        self.flush()
        self._publish(epoch, self._encode(objects), sorted(objects),
                      step=step, kind=kind)

    # -- background publish ------------------------------------------------
    def save_async(self, epoch: int, objects: Dict[str, object],
                   step: Optional[int] = None,
                   kind: str = "step") -> None:
        """Capture now (caller thread), publish on the store's
        background thread.  Failures are warned + counted
        (``checkpoint.async_errors``) rather than raised into the step
        loop; :meth:`flush` at sync points surfaces durability."""
        fault.point("ckpt.save", self.dir, epoch)
        job = {"epoch": int(epoch), "files": self._encode(objects),
               "object_names": sorted(objects), "step": step,
               "kind": kind}
        with self._pub_cv:
            if self._pub_thread is None or not self._pub_thread.is_alive():
                import queue
                self._pub_queue = queue.SimpleQueue()
                self._pub_thread = threading.Thread(
                    target=self._publish_loop, name="snapshot-publisher",
                    daemon=True)
                self._pub_thread.start()
            self._pub_pending += 1
        self._pub_queue.put(job)
        monitor.stat_add("checkpoint.async_saves")

    def _publish_loop(self) -> None:
        while True:
            job = self._pub_queue.get()
            if job is None:
                return
            try:
                self._publish(**job)
            except BaseException as e:  # noqa: BLE001 - kept, not raised
                with self._pub_cv:
                    self._pub_error = e
                monitor.stat_add("checkpoint.async_errors")
                warnings.warn(
                    f"checkpoint: background publish of "
                    f"{job.get('kind')} snapshot (epoch {job.get('epoch')}"
                    f", step {job.get('step')}) failed: {e}")
            finally:
                with self._pub_cv:
                    self._pub_pending -= 1
                    self._pub_cv.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until every queued background publish has landed.
        Returns False on timeout.  A failed publish was already warned;
        the next *sync* save surfaces a persistently broken store."""
        with self._pub_cv:
            return self._pub_cv.wait_for(
                lambda: self._pub_pending == 0, timeout)

    # -- restore -----------------------------------------------------------
    def _read_file_verified(self, snap: dict, fname: str,
                            digests: Optional[dict]) -> Optional[bytes]:
        path = self._join(snap["dir"], fname)
        try:
            with self._fs.open_read(path) as f:
                payload = f.read()
        except (OSError, RuntimeError) as e:
            warnings.warn(f"checkpoint {snap['dir']}: cannot read "
                          f"'{fname}': {e}")
            return None
        if self.verify and digests is not None:
            got = hashlib.sha256(payload).hexdigest()
            if got != digests[fname]:
                warnings.warn(
                    f"checkpoint {snap['dir']}: sha256 mismatch for "
                    f"'{fname}' (stored {digests[fname][:12]}…, "
                    f"recomputed {got[:12]}…)")
                return None
        return payload

    def _read_verified(self, snap: dict,
                       objects: Dict[str, object]) -> Optional[dict]:
        """All payloads of one snapshot, digest-checked — or None with a
        warning naming what failed (missing file, bad hash).  Sharded
        objects (saved through the ``shard_state`` protocol) come back
        as ``("__sharded__", manifest, {fname: bytes})``; every shard
        file is verified against its own digest."""
        digests = snap.get("digests")
        payloads = {}
        for name in objects:
            mf = f"{name}.manifest.json"
            if digests is not None and mf in digests:
                raw = self._read_file_verified(snap, mf, digests)
                if raw is None:
                    return None
                try:
                    manifest = json.loads(raw.decode("utf-8"))
                except ValueError as e:
                    warnings.warn(f"checkpoint {snap['dir']}: corrupt "
                                  f"manifest '{mf}': {e}")
                    return None
                shard_files = [sh["file"]
                               for leaf in manifest.get("leaves", [])
                               for sh in leaf.get("shards", [])]
                blobs = {}
                for fname in shard_files:
                    full = f"{name}.{fname}"
                    if full not in digests:
                        warnings.warn(
                            f"checkpoint {snap['dir']}: manifest names "
                            f"'{full}' but it carries no digest")
                        return None
                    data = self._read_file_verified(snap, full, digests)
                    if data is None:
                        return None
                    blobs[fname] = data
                payloads[name] = ("__sharded__", manifest, blobs)
                continue
            fname = f"{name}.pdparams"
            if digests is not None and fname not in digests:
                warnings.warn(
                    f"checkpoint {snap['dir']}: registered object "
                    f"'{name}' was never saved in this snapshot")
                return None
            payload = self._read_file_verified(snap, fname, digests)
            if payload is None:
                return None
            payloads[name] = payload
        return payloads

    def restore(self, objects: Dict[str, object]) -> int:
        """Load the newest intact snapshot into ``objects`` and return
        the next epoch to run (for a mid-epoch *step* snapshot: the
        epoch to re-enter — its ``step`` is on :attr:`last_restored`).
        Falls back across the retained history; raises
        :class:`CheckpointError` when a checkpoint exists but no
        snapshot verifies — never resumes half-initialized."""
        self.last_restored = None
        meta = self.load_meta()
        if meta is None:
            return 0
        attempts = []
        for snap in reversed(meta["snapshots"]):
            fault.point("ckpt.restore", self.dir, snap.get("dir"))
            payloads = self._read_verified(snap, objects)
            if payloads is None:
                attempts.append(str(snap.get("dir")))
                monitor.stat_add("checkpoint.fallbacks")
                trc = _obs_hook._tracer
                if trc is not None:
                    trc.emit("checkpoint", "fallback",
                             args={"snapshot": str(snap.get("dir")),
                                   "dir": self.dir})
                continue
            # decode everything BEFORE applying anything: a corrupt
            # payload that slipped past hashing still can't part-load
            states = {}
            for name, p in payloads.items():
                if isinstance(p, tuple) and p[0] == "__sharded__":
                    _, manifest, blobs = p
                    decoded = {f: _loads(
                        b, source=f"{snap['dir']}/{name}.{f}")
                        for f, b in blobs.items()}
                    states[name] = ("__sharded__", manifest, decoded)
                else:
                    states[name] = _loads(
                        p, source=f"{snap['dir']}/{name}")
            for name, obj in objects.items():
                st = states[name]
                if isinstance(st, tuple) and st[0] == "__sharded__":
                    # reshard onto whatever mesh is live NOW (gather-
                    # free when the stored layout already matches)
                    obj.load_shard_state(st[1], st[2])
                else:
                    obj.set_state_dict(st)
            if attempts:
                warnings.warn(
                    f"checkpoint: snapshot(s) {attempts} failed "
                    f"verification; resumed from older intact "
                    f"'{snap['dir']}' (epoch {snap['epoch']})")
            monitor.stat_add("checkpoint.restores")
            trc = _obs_hook._tracer
            if trc is not None:
                trc.emit("checkpoint", "restore",
                         args={"epoch": int(snap["epoch"]),
                               "snapshot": str(snap["dir"]),
                               "step": snap.get("step"),
                               "fell_back_past": attempts})
            self.last_restored = dict(snap)
            if snap.get("kind") == "step":
                return int(snap["epoch"])       # re-enter mid-epoch
            return int(snap["epoch"]) + 1
        raise CheckpointError(
            f"checkpoint dir '{self.dir}' has a published meta but no "
            f"intact snapshot (tried {attempts}); refusing to resume "
            f"half-initialized — delete the dir to restart from scratch")

    # -- polling consumers (serving weight hot swap) -----------------------
    def latest_snapshot(self) -> Optional[dict]:
        """Newest published snapshot's meta entry (dict with ``dir`` /
        ``epoch`` / ``step`` / ``digests``), or None when nothing has
        been published — the cheap poll a serving-side
        :class:`~paddle_tpu.serving.hotswap.WeightWatcher` issues to
        notice new weights without reading any payload."""
        meta = self.load_meta()
        if meta is None or not meta.get("snapshots"):
            return None
        return dict(meta["snapshots"][-1])

    def load_payloads(self, names: Sequence[str],
                      snap: Optional[dict] = None) -> Optional[dict]:
        """Read + sha256-verify + decode the named payloads of one
        snapshot (default: the newest) WITHOUT applying them to any
        object — the serving half of a weight hot swap loads here, off
        the dispatch thread, and only commits what verified.

        Returns ``{name: decoded state-dict}``, or None when the
        snapshot is missing/corrupt/partial (a warning names what
        failed) — rejection, not exception, so a polling consumer can
        keep serving the version it already has.  Sharded payloads are
        refused (serving replicas load replicated weights)."""
        if snap is None:
            snap = self.latest_snapshot()
            if snap is None:
                return None
        payloads = self._read_verified(snap, {n: None for n in names})
        if payloads is None:
            return None
        out = {}
        for name, p in payloads.items():
            if isinstance(p, tuple) and p[0] == "__sharded__":
                warnings.warn(
                    f"checkpoint {snap.get('dir')}: payload '{name}' is "
                    f"sharded; load_payloads serves replicated weights "
                    f"only")
                return None
            try:
                out[name] = _loads(p, source=f"{snap.get('dir')}/{name}")
            except Exception as e:      # decode failure == corruption
                warnings.warn(f"checkpoint {snap.get('dir')}: payload "
                              f"'{name}' failed to decode: {e}")
                return None
        return out


class TrainEpochRange:
    """Iterable of epoch indices with save-on-epoch-end and auto-resume.

    Usage::

        r = TrainEpochRange(10, "ckpt/run1", model=model, opt=opt)
        for epoch in r:          # resumes after the last finished epoch
            train_one_epoch(...)

    ``keep_checkpoint_max`` snapshots are retained (default
    ``FLAGS_checkpoint_keep_max``); restore verifies sha256 digests and
    falls back across them.  While iterating (main thread), SIGTERM —
    the cloud-TPU preemption notice — requests a snapshot at the next
    epoch boundary, publishes it, then exits via ``SystemExit(0)``
    (disable with ``handle_preemption=False``).

    Step cadence: with ``save_every_steps`` and/or ``save_every_s``
    set, call :meth:`step` once per training step.  Due snapshots are
    captured on the step thread and published on the store's
    background thread (``async_publish=False`` keeps them fully
    synchronous); a pending SIGTERM then saves at the next **step**
    boundary instead of waiting for the epoch to end.  After a
    restart, :attr:`resume_step` is the global step to continue from
    (the restored snapshot's step count)."""

    def __init__(self, max_epoch_num: int, checkpoint_dir: str,
                 save_checkpoint_inter: int = 1,
                 keep_checkpoint_max: Optional[int] = None,
                 verify: bool = True, handle_preemption: bool = True,
                 save_every_steps: Optional[int] = None,
                 save_every_s: Optional[float] = None,
                 async_publish: bool = True,
                 **objects):
        self.max_epoch = int(max_epoch_num)
        self.dir = checkpoint_dir
        self.interval = max(1, int(save_checkpoint_inter))
        self.handle_preemption = handle_preemption
        self.save_every_steps = (None if save_every_steps is None
                                 else max(1, int(save_every_steps)))
        self.save_every_s = (None if save_every_s is None
                             else float(save_every_s))
        self.async_publish = async_publish
        self._objects: Dict[str, object] = dict(objects)
        self._store = SnapshotStore(checkpoint_dir,
                                    keep_max=keep_checkpoint_max,
                                    verify=verify)
        self._fs = self._store._fs
        self._preempted = threading.Event()
        self._global_step = 0
        self._resume_step = 0
        self._cur_epoch = 0
        self._last_save_step = 0
        self._last_save_t = time.monotonic()

    def register(self, name: str, obj):
        """Add a state_dict-bearing object to the snapshot set."""
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _save(self, epoch: int):
        self._store.save(epoch, self._objects)
        self._last_save_step = self._global_step
        self._last_save_t = time.monotonic()

    def _restore(self) -> int:
        start = self._store.restore(self._objects)
        snap = self._store.last_restored or {}
        self._global_step = self._resume_step = int(snap.get("step") or 0)
        self._last_save_step = self._global_step
        self._last_save_t = time.monotonic()
        return start

    def _load_meta(self) -> Optional[dict]:
        return self._store.load_meta()

    # -- step cadence ------------------------------------------------------
    @property
    def resume_step(self) -> int:
        """Global step to continue from after restore (0 = fresh)."""
        return self._resume_step

    @property
    def global_step(self) -> int:
        return self._global_step

    def _save_step_snapshot(self, sync: bool) -> None:
        if sync:
            self._store.save(self._cur_epoch, self._objects,
                             step=self._global_step, kind="step")
        else:
            self._store.save_async(self._cur_epoch, self._objects,
                                   step=self._global_step, kind="step")
        self._last_save_step = self._global_step
        self._last_save_t = time.monotonic()
        monitor.stat_add("checkpoint.step_saves")

    def step(self) -> int:
        """Mark one training step complete; returns the global step.

        Drives the step-cadence snapshots and — when a SIGTERM arrived
        — the step-boundary preemption save (synchronous publish, then
        ``SystemExit(0)``), so a preempted or supervisor-killed run
        loses at most the in-flight step instead of the epoch."""
        self._global_step += 1
        if self.handle_preemption and self._preempted.is_set():
            self._save_step_snapshot(sync=True)
            monitor.stat_add("checkpoint.preempt_saves")
            raise SystemExit(0)
        due = (self.save_every_steps is not None
               and self._global_step - self._last_save_step
               >= self.save_every_steps)
        if not due and self.save_every_s is not None:
            due = (time.monotonic() - self._last_save_t
                   >= self.save_every_s)
        if due:
            self._save_step_snapshot(sync=not self.async_publish)
        return self._global_step

    # -- preemption --------------------------------------------------------
    @property
    def preempted(self) -> bool:
        """True once a SIGTERM asked for a boundary save + clean exit."""
        return self._preempted.is_set()

    def _on_preempt(self):
        self._preempted.set()
        monitor.stat_add("checkpoint.preempt_requests")
        trc = _obs_hook._tracer
        if trc is not None:
            trc.emit("checkpoint", "preempt_request",
                     args={"dir": self.dir})

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self._restore()
        restore_handler = (install_preemption_handler(self._on_preempt)
                           if self.handle_preemption else None)
        try:
            for epoch in range(start, self.max_epoch):
                self._cur_epoch = epoch
                yield epoch
                # body finished without raising: snapshot this epoch
                if (self._preempted.is_set()
                        or (epoch + 1) % self.interval == 0
                        or epoch == self.max_epoch - 1):
                    self._save(epoch)
                if self._preempted.is_set():
                    monitor.stat_add("checkpoint.preempt_saves")
                    raise SystemExit(0)
        finally:
            # queued background publishes land before the loop returns
            # (or unwinds) — a completed range never leaves a captured
            # snapshot unpublished
            self._store.flush()
            if restore_handler is not None:
                restore_handler()

    @property
    def next_epoch(self) -> int:
        meta = self._load_meta()
        return 0 if meta is None else int(meta["finished_epoch"]) + 1


def train_epoch_range(max_epoch_num: int, checkpoint_dir: str = "./acp",
                      save_checkpoint_inter: int = 1,
                      **objects) -> TrainEpochRange:
    """reference: auto_checkpoint.py train_epoch_range:71."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir,
                           save_checkpoint_inter, **objects)
