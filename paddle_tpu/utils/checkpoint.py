"""Auto-checkpointed training ranges.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range:71, TrainEpochRange save/restore:265) — epoch loops
that snapshot registered state and resume transparently after a restart.

TPU-native: state is whatever exposes ``state_dict``/``set_state_dict``
(Layers, optimizers, GradScalers, LR schedules); snapshots go through
``paddle_tpu.save`` (npz pytrees) plus a small json meta, written
atomically (tmp + rename) so a preemption mid-save can't corrupt the
latest checkpoint.  ``checkpoint_dir`` may carry a registered filesystem
scheme (``hdfs://...`` — utils/fs.py, reference framework/io/fs.cc), so
fleet preemption recovery can land on a remote store.

Integrity tier: every snapshot file's sha256 lands in the published
meta and is re-verified on restore — a corrupt or missing file NEVER
part-loads; restore falls back to the previous intact snapshot (the
meta keeps the last ``keep_checkpoint_max``) or raises
:class:`CheckpointError` loudly.  A SIGTERM (the TPU-pod preemption
notice) requests a save at the next epoch boundary, publishes it, and
exits cleanly — ``tools/chaos_smoke.py`` proves the round trip.
Recovery events surface in ``monitor`` stats (``checkpoint.saves``,
``checkpoint.fallbacks``, ``checkpoint.preempt_saves``).
"""
from __future__ import annotations

import hashlib
import json
import signal
import threading
import warnings
from typing import Dict, Iterator, List, Optional

from . import fs as _fsmod
from . import monitor
from ..core import flags as _flags
from ..core import obs_hook as _obs_hook
from ..framework_io import dumps as _dumps
from ..framework_io import loads as _loads
from ..testing import fault

__all__ = ["CheckpointError", "SnapshotStore", "TrainEpochRange",
           "install_preemption_handler", "train_epoch_range"]


class CheckpointError(RuntimeError):
    """No intact snapshot could be restored (corrupt/missing state)."""


def install_preemption_handler(on_term):
    """Install a SIGTERM handler that calls ``on_term()`` then chains to
    the previous Python handler.  Returns a ``restore()`` callable, or
    None when installation isn't possible (non-main thread, or the
    previous handler was installed by non-Python code — ``getsignal``
    returns None — which we could neither chain nor restore)."""
    if threading.current_thread() is not threading.main_thread():
        return None
    prev = signal.getsignal(signal.SIGTERM)
    if prev is None:
        return None

    def _handler(signum, frame):
        on_term()
        # chain: give outer handlers (fleet agents) their notice too
        if callable(prev) and prev not in (signal.SIG_IGN,
                                           signal.SIG_DFL):
            prev(signum, frame)

    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:      # non-main interpreter thread raced us
        return None

    def restore():
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, TypeError):
            pass

    return restore


class SnapshotStore:
    """Versioned, digest-verified snapshot directory.

    Layout: ``<dir>/epoch_<n>/<name>.pdparams`` per registered object,
    published atomically through ``<dir>/range_meta.json`` whose
    ``snapshots`` list carries per-file sha256 digests.  Keeps the last
    ``keep_max`` snapshots so a corrupt latest can fall back."""

    META = "range_meta.json"

    def __init__(self, directory: str, keep_max: Optional[int] = None,
                 verify: bool = True):
        self.dir = directory
        self.keep_max = max(1, int(
            keep_max if keep_max is not None
            else _flags.get_flag("checkpoint_keep_max")))
        self.verify = verify
        self._fs = _fsmod.get_fs(directory)
        self._fs.mkdir(directory)

    def _join(self, *parts) -> str:
        return "/".join([self.dir.rstrip("/")] + list(parts))

    def _meta_path(self) -> str:
        return self._join(self.META)

    def load_meta(self) -> Optional[dict]:
        try:
            with self._fs.open_read(self._meta_path()) as f:
                meta = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, RuntimeError):
            return None
        # v1 metas (pre-digest) carried only the latest snapshot
        if "snapshots" not in meta:
            meta["snapshots"] = [{"epoch": int(meta.get(
                "finished_epoch", -1)), "dir": meta.get("snapshot", ""),
                "digests": None}]
        return meta

    # -- save --------------------------------------------------------------
    def save(self, epoch: int, objects: Dict[str, object]) -> None:
        fault.point("ckpt.save", self.dir, epoch)
        snap = f"epoch_{epoch}"
        sdir = self._join(snap)
        self._fs.mkdir(sdir)
        digests = {}
        for name, obj in objects.items():
            if hasattr(obj, "shard_state"):
                # sharded protocol (distributed/sharding.ShardedState):
                # one payload per unique shard + a manifest naming them
                # — every file gets its own digest, so a single corrupt
                # shard is caught without touching the others
                manifest, payloads = obj.shard_state()
                files = {f"{name}.manifest.json": json.dumps(
                    manifest).encode("utf-8")}
                for fname, data in payloads.items():
                    files[f"{name}.{fname}"] = data
                for fname, data in files.items():
                    digests[fname] = hashlib.sha256(data).hexdigest()
                    _fsmod.write_atomic(f"{sdir}/{fname}", data)
                continue
            payload = _dumps(obj.state_dict())
            digests[f"{name}.pdparams"] = hashlib.sha256(
                payload).hexdigest()
            _fsmod.write_atomic(f"{sdir}/{name}.pdparams", payload)
        meta = self.load_meta() or {"snapshots": []}
        snaps = [s for s in meta["snapshots"] if s.get("dir") != snap]
        snaps.append({"epoch": int(epoch), "dir": snap,
                      "digests": digests})
        snaps = snaps[-self.keep_max:]
        meta = {"finished_epoch": int(epoch), "snapshot": snap,
                "objects": sorted(objects), "snapshots": snaps}
        fault.point("ckpt.publish", self.dir, epoch)
        _fsmod.write_atomic(self._meta_path(),
                            json.dumps(meta).encode("utf-8"))
        monitor.stat_add("checkpoint.saves")
        trc = _obs_hook._tracer
        if trc is not None:
            trc.emit("checkpoint", "save",
                     args={"epoch": int(epoch), "dir": self.dir})
        keep = {s["dir"] for s in snaps}
        for d in self._fs.list(self.dir):
            if d.startswith("epoch_") and d not in keep:
                try:
                    self._fs.remove(self._join(d))
                except (RuntimeError, OSError):
                    pass  # prune is best-effort (shared dirs, perms)

    # -- restore -----------------------------------------------------------
    def _read_file_verified(self, snap: dict, fname: str,
                            digests: Optional[dict]) -> Optional[bytes]:
        path = self._join(snap["dir"], fname)
        try:
            with self._fs.open_read(path) as f:
                payload = f.read()
        except (OSError, RuntimeError) as e:
            warnings.warn(f"checkpoint {snap['dir']}: cannot read "
                          f"'{fname}': {e}")
            return None
        if self.verify and digests is not None:
            got = hashlib.sha256(payload).hexdigest()
            if got != digests[fname]:
                warnings.warn(
                    f"checkpoint {snap['dir']}: sha256 mismatch for "
                    f"'{fname}' (stored {digests[fname][:12]}…, "
                    f"recomputed {got[:12]}…)")
                return None
        return payload

    def _read_verified(self, snap: dict,
                       objects: Dict[str, object]) -> Optional[dict]:
        """All payloads of one snapshot, digest-checked — or None with a
        warning naming what failed (missing file, bad hash).  Sharded
        objects (saved through the ``shard_state`` protocol) come back
        as ``("__sharded__", manifest, {fname: bytes})``; every shard
        file is verified against its own digest."""
        digests = snap.get("digests")
        payloads = {}
        for name in objects:
            mf = f"{name}.manifest.json"
            if digests is not None and mf in digests:
                raw = self._read_file_verified(snap, mf, digests)
                if raw is None:
                    return None
                try:
                    manifest = json.loads(raw.decode("utf-8"))
                except ValueError as e:
                    warnings.warn(f"checkpoint {snap['dir']}: corrupt "
                                  f"manifest '{mf}': {e}")
                    return None
                shard_files = [sh["file"]
                               for leaf in manifest.get("leaves", [])
                               for sh in leaf.get("shards", [])]
                blobs = {}
                for fname in shard_files:
                    full = f"{name}.{fname}"
                    if full not in digests:
                        warnings.warn(
                            f"checkpoint {snap['dir']}: manifest names "
                            f"'{full}' but it carries no digest")
                        return None
                    data = self._read_file_verified(snap, full, digests)
                    if data is None:
                        return None
                    blobs[fname] = data
                payloads[name] = ("__sharded__", manifest, blobs)
                continue
            fname = f"{name}.pdparams"
            if digests is not None and fname not in digests:
                warnings.warn(
                    f"checkpoint {snap['dir']}: registered object "
                    f"'{name}' was never saved in this snapshot")
                return None
            payload = self._read_file_verified(snap, fname, digests)
            if payload is None:
                return None
            payloads[name] = payload
        return payloads

    def restore(self, objects: Dict[str, object]) -> int:
        """Load the newest intact snapshot into ``objects`` and return
        the next epoch to run.  Falls back across the retained history;
        raises :class:`CheckpointError` when a checkpoint exists but no
        snapshot verifies — never resumes half-initialized."""
        meta = self.load_meta()
        if meta is None:
            return 0
        attempts = []
        for snap in reversed(meta["snapshots"]):
            fault.point("ckpt.restore", self.dir, snap.get("dir"))
            payloads = self._read_verified(snap, objects)
            if payloads is None:
                attempts.append(str(snap.get("dir")))
                monitor.stat_add("checkpoint.fallbacks")
                trc = _obs_hook._tracer
                if trc is not None:
                    trc.emit("checkpoint", "fallback",
                             args={"snapshot": str(snap.get("dir")),
                                   "dir": self.dir})
                continue
            # decode everything BEFORE applying anything: a corrupt
            # payload that slipped past hashing still can't part-load
            states = {}
            for name, p in payloads.items():
                if isinstance(p, tuple) and p[0] == "__sharded__":
                    _, manifest, blobs = p
                    decoded = {f: _loads(
                        b, source=f"{snap['dir']}/{name}.{f}")
                        for f, b in blobs.items()}
                    states[name] = ("__sharded__", manifest, decoded)
                else:
                    states[name] = _loads(
                        p, source=f"{snap['dir']}/{name}")
            for name, obj in objects.items():
                st = states[name]
                if isinstance(st, tuple) and st[0] == "__sharded__":
                    # reshard onto whatever mesh is live NOW (gather-
                    # free when the stored layout already matches)
                    obj.load_shard_state(st[1], st[2])
                else:
                    obj.set_state_dict(st)
            if attempts:
                warnings.warn(
                    f"checkpoint: snapshot(s) {attempts} failed "
                    f"verification; resumed from older intact "
                    f"'{snap['dir']}' (epoch {snap['epoch']})")
            monitor.stat_add("checkpoint.restores")
            trc = _obs_hook._tracer
            if trc is not None:
                trc.emit("checkpoint", "restore",
                         args={"epoch": int(snap["epoch"]),
                               "snapshot": str(snap["dir"]),
                               "fell_back_past": attempts})
            return int(snap["epoch"]) + 1
        raise CheckpointError(
            f"checkpoint dir '{self.dir}' has a published meta but no "
            f"intact snapshot (tried {attempts}); refusing to resume "
            f"half-initialized — delete the dir to restart from scratch")


class TrainEpochRange:
    """Iterable of epoch indices with save-on-epoch-end and auto-resume.

    Usage::

        r = TrainEpochRange(10, "ckpt/run1", model=model, opt=opt)
        for epoch in r:          # resumes after the last finished epoch
            train_one_epoch(...)

    ``keep_checkpoint_max`` snapshots are retained (default
    ``FLAGS_checkpoint_keep_max``); restore verifies sha256 digests and
    falls back across them.  While iterating (main thread), SIGTERM —
    the cloud-TPU preemption notice — requests a snapshot at the next
    epoch boundary, publishes it, then exits via ``SystemExit(0)``
    (disable with ``handle_preemption=False``)."""

    def __init__(self, max_epoch_num: int, checkpoint_dir: str,
                 save_checkpoint_inter: int = 1,
                 keep_checkpoint_max: Optional[int] = None,
                 verify: bool = True, handle_preemption: bool = True,
                 **objects):
        self.max_epoch = int(max_epoch_num)
        self.dir = checkpoint_dir
        self.interval = max(1, int(save_checkpoint_inter))
        self.handle_preemption = handle_preemption
        self._objects: Dict[str, object] = dict(objects)
        self._store = SnapshotStore(checkpoint_dir,
                                    keep_max=keep_checkpoint_max,
                                    verify=verify)
        self._fs = self._store._fs
        self._preempted = threading.Event()

    def register(self, name: str, obj):
        """Add a state_dict-bearing object to the snapshot set."""
        self._objects[name] = obj
        return self

    # -- persistence -------------------------------------------------------
    def _save(self, epoch: int):
        self._store.save(epoch, self._objects)

    def _restore(self) -> int:
        return self._store.restore(self._objects)

    def _load_meta(self) -> Optional[dict]:
        return self._store.load_meta()

    # -- preemption --------------------------------------------------------
    @property
    def preempted(self) -> bool:
        """True once a SIGTERM asked for a boundary save + clean exit."""
        return self._preempted.is_set()

    def _on_preempt(self):
        self._preempted.set()
        monitor.stat_add("checkpoint.preempt_requests")
        trc = _obs_hook._tracer
        if trc is not None:
            trc.emit("checkpoint", "preempt_request",
                     args={"dir": self.dir})

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        start = self._restore()
        restore_handler = (install_preemption_handler(self._on_preempt)
                           if self.handle_preemption else None)
        try:
            for epoch in range(start, self.max_epoch):
                yield epoch
                # body finished without raising: snapshot this epoch
                if (self._preempted.is_set()
                        or (epoch + 1) % self.interval == 0
                        or epoch == self.max_epoch - 1):
                    self._save(epoch)
                if self._preempted.is_set():
                    monitor.stat_add("checkpoint.preempt_saves")
                    raise SystemExit(0)
        finally:
            if restore_handler is not None:
                restore_handler()

    @property
    def next_epoch(self) -> int:
        meta = self._load_meta()
        return 0 if meta is None else int(meta["finished_epoch"]) + 1


def train_epoch_range(max_epoch_num: int, checkpoint_dir: str = "./acp",
                      save_checkpoint_inter: int = 1,
                      **objects) -> TrainEpochRange:
    """reference: auto_checkpoint.py train_epoch_range:71."""
    return TrainEpochRange(max_epoch_num, checkpoint_dir,
                           save_checkpoint_inter, **objects)
