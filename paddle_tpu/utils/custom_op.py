"""Custom-op registration.

Reference: the C++ custom-operator extension path
(paddle/fluid/framework/custom_operator.cc, python/paddle/utils/
cpp_extension) where users compile kernels against the framework ABI.
TPU-native re-design: a custom op is a PURE jnp/lax/Pallas function —
registering it wires it through the shared dispatch point so it gets
tape recording, AMP casting, profiling, and static-graph capture exactly
like built-in ops.  A custom backward is a ``jax.custom_vjp`` pair,
usable for ops whose gradient XLA cannot derive.

NATIVE kernels: compile C++ against the XLA FFI with
:mod:`paddle_tpu.utils.cpp_extension` (``load(name, sources,
functions)``) — the returned callables are pure jax fns and register
here like any other, including a native backward as the vjp pair
(tests/test_cpp_extension.py shows the full fwd+bwd flow).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from ..core.dispatch import apply

__all__ = ["register_custom_op"]

_registry = {}


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None) -> Callable:
    """Register ``forward(*arrays, **attrs) -> array(s)`` as op ``name``.

    ``backward(res, grad_out) -> tuple(grads)`` with ``res`` the tuple of
    forward inputs, if given, overrides autodiff via jax.custom_vjp —
    the analog of defining a GradOpMaker for a C++ custom op.

    Returns the op callable (Tensor in / Tensor out); also registered
    under ``name`` for lookup via :func:`get_custom_op`.
    """
    if backward is not None:
        core = jax.custom_vjp(forward)

        def fwd(*args, **kw):
            return forward(*args, **kw), args

        def bwd(res, ct):
            return tuple(backward(res, ct))

        core.defvjp(fwd, bwd)
    else:
        core = forward

    def op(*tensors, **attrs):
        return apply(core, *tensors, op_name=name, **attrs)

    op.__name__ = name
    _registry[name] = op
    return op


def get_custom_op(name: str) -> Callable:
    return _registry[name]
