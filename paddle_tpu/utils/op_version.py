"""Op version registry.

Reference: paddle/fluid/framework/op_version_registry.h — per-op version
numbers + change notes consumed by model-compat checks at load time.
Here versions ride in the jit.save / save_inference_model meta (StableHLO
itself is the version-stable serialization layer, so this registry is
metadata for humans and compat tooling, not a kernel selector)."""
from __future__ import annotations

from typing import Dict, List, NamedTuple

__all__ = ["OpVersion", "register_op_version", "get_op_version",
           "all_op_versions"]


class OpVersion(NamedTuple):
    version: int
    notes: List[str]


_registry: Dict[str, OpVersion] = {}


def register_op_version(op_name: str, version: int = 1, note: str = ""):
    prev = _registry.get(op_name)
    notes = (list(prev.notes) if prev else [])
    if note:
        notes.append(note)
    _registry[op_name] = OpVersion(version, notes)
    return _registry[op_name]


def get_op_version(op_name: str) -> int:
    v = _registry.get(op_name)
    return v.version if v else 0


def all_op_versions() -> Dict[str, int]:
    return {k: v.version for k, v in _registry.items()}
