"""Filesystem abstraction for checkpoints/models.

Reference: framework/io/fs.h/.cc — localfs_* + hdfs_* entry points where
HDFS operations shell out to the ``hadoop fs`` CLI (fs.cc hdfs_open_read
pipes through ``{hadoop} fs -text``), selected per path by
``fs_select_internal`` (hdfs:// vs afs:// vs local prefix).

TPU-native shape: one :class:`FileSystem` protocol, a scheme registry
(``register_fs``), and the same path-prefix dispatch.  ``paddle.save`` /
``paddle.load`` / auto-checkpoint route every byte through
:func:`open_read` / :func:`open_write`, so a cluster user can point
checkpoints at ``hdfs://...`` (or register an S3/GCS adapter) without
touching training code — the preemption-recovery capability fs.cc exists
for."""
from __future__ import annotations

import io
import os
import shutil
import subprocess
import tempfile
from typing import Callable, Dict, List


class FileSystem:
    """Protocol: byte-level ops a checkpoint store needs (fs.h surface)."""

    def open_read(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def open_write(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        raise NotImplementedError

    def mv(self, src: str, dst: str) -> None:
        raise NotImplementedError


class LocalFS(FileSystem):
    """fs.cc localfs_*: plain files + atomic-rename mv."""

    def open_read(self, path):
        return open(path, "rb")

    def open_write(self, path):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")

    def exists(self, path):
        return os.path.exists(path)

    def mkdir(self, path):
        os.makedirs(path, exist_ok=True)

    def remove(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def list(self, path):
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def mv(self, src, dst):
        os.replace(src, dst)


class ShellFS(FileSystem):
    """HDFS-style filesystem driven through a shell CLI (fs.cc hdfs_*:
    every op is ``{command} fs -<verb>``).  ``command`` defaults to the
    ``hadoop`` binary; AFS or other HDFS-compatible stores override it
    (the reference's HADOOP_HOME + ugi configs)."""

    def __init__(self, command: str = "hadoop"):
        self.command = command

    def _run(self, *args, input_bytes=None, capture=True):
        try:
            return subprocess.run(
                [self.command, "fs", *args], input=input_bytes,
                capture_output=capture, check=True)
        except FileNotFoundError as e:
            raise RuntimeError(
                f"ShellFS: '{self.command}' CLI not found — install it or "
                f"register a different FileSystem for this scheme "
                f"(paddle_tpu.utils.fs.register_fs)") from e
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"ShellFS: {self.command} fs {' '.join(args)} failed: "
                f"{(e.stderr or b'').decode(errors='replace')[:500]}") from e

    def open_read(self, path):
        out = self._run("-cat", path)
        return io.BytesIO(out.stdout)

    def open_write(self, path):
        fs = self

        class _Buf(io.BytesIO):
            def close(self_inner):
                data = self_inner.getvalue()
                fs._run("-put", "-f", "-", path, input_bytes=data)
                super().close()

        return _Buf()

    def exists(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except RuntimeError:
            return False

    def mkdir(self, path):
        self._run("-mkdir", "-p", path)

    def remove(self, path):
        self._run("-rm", "-r", "-f", path)

    def list(self, path):
        out = self._run("-ls", path).stdout.decode(errors="replace")
        names = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                names.append(parts[-1].rsplit("/", 1)[-1])
        return sorted(names)

    def mv(self, src, dst):
        # HDFS rename refuses to overwrite; emulate os.replace with a
        # delete-then-rename (weaker atomicity than LocalFS — the window
        # between rm and mv can leave no meta; readers treat a missing
        # meta as 'no checkpoint yet', which the resume path tolerates)
        try:
            self._run("-rm", "-f", dst)
        except RuntimeError:
            pass
        self._run("-mv", src, dst)


_REGISTRY: Dict[str, FileSystem] = {}
_LOCAL = LocalFS()


def register_fs(scheme: str, fs: FileSystem) -> None:
    """Register a filesystem for a path scheme (``'hdfs'``, ``'s3'``...)."""
    _REGISTRY[scheme.rstrip(":/")] = fs


register_fs("hdfs", ShellFS("hadoop"))
register_fs("afs", ShellFS("hadoop"))


def get_fs(path: str) -> FileSystem:
    """fs_select_internal parity: pick the filesystem by path prefix."""
    if "://" in path:
        scheme = path.split("://", 1)[0]
        fs = _REGISTRY.get(scheme)
        if fs is None:
            raise ValueError(
                f"no FileSystem registered for scheme '{scheme}://' — "
                f"register one with paddle_tpu.utils.fs.register_fs")
        return fs
    return _LOCAL


def open_read(path: str):
    return get_fs(path).open_read(path)


def open_write(path: str):
    return get_fs(path).open_write(path)


def exists(path: str) -> bool:
    return get_fs(path).exists(path)
