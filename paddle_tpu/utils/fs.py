"""Filesystem abstraction for checkpoints/models.

Reference: framework/io/fs.h/.cc — localfs_* + hdfs_* entry points where
HDFS operations shell out to the ``hadoop fs`` CLI (fs.cc hdfs_open_read
pipes through ``{hadoop} fs -text``), selected per path by
``fs_select_internal`` (hdfs:// vs afs:// vs local prefix).

TPU-native shape: one :class:`FileSystem` protocol, a scheme registry
(``register_fs``), and the same path-prefix dispatch.  ``paddle.save`` /
``paddle.load`` / auto-checkpoint route every byte through
:func:`open_read` / :func:`open_write`, so a cluster user can point
checkpoints at ``hdfs://...`` (or register an S3/GCS adapter) without
touching training code — the preemption-recovery capability fs.cc exists
for.

Robustness tier (reference fs.cc retries every hdfs op via
``fs_retry_times``): remote ops fail transiently all the time on a busy
cluster, so errors are CLASSIFIED (:class:`TransientFSError` vs
:class:`PermanentFSError`) and transient ones retried with exponential
backoff + jitter under a wall-clock deadline (``FLAGS_fs_retry_times`` /
``FLAGS_fs_retry_backoff_s`` / ``FLAGS_fs_retry_deadline_s``).  ShellFS
retries built-in; any registered filesystem opts in via
``register_fs(scheme, fs, retry=True)`` (a :class:`RetryingFS` wrap).
Every retry shows up in ``monitor`` stats ``fs.retries`` / ``fs.gave_up``.
``paddle_tpu.testing.fault`` points (``fs.<op>``, ``fs.shell_run``) sit
inside the retry scope so chaos tests can prove the loop works."""
from __future__ import annotations

import errno
import io
import os
import random
import shutil
import subprocess
import time
from typing import Dict, List

from ..testing import fault


class FSError(RuntimeError):
    """Base class for classified filesystem errors."""


class TransientFSError(FSError):
    """Error worth retrying: network blips, timeouts, busy services."""


class PermanentFSError(FSError):
    """Error retries cannot fix: missing paths, permissions, bad args."""


_TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.EINTR, errno.EBUSY, errno.ETIMEDOUT,
    errno.ECONNRESET, errno.ECONNREFUSED, errno.ECONNABORTED,
    errno.ENETUNREACH, errno.ENETRESET, errno.EHOSTUNREACH,
    errno.EPIPE, errno.EIO,
})

# Errnos no amount of backoff can fix: a full disk, a read-only mount,
# a blown quota.  These fail FAST as PermanentFSError — spending the
# whole FLAGS_fs_retry_deadline_s on them just delays the operator
# learning the volume is full.
_PERMANENT_ERRNOS = frozenset({
    errno.ENOSPC, errno.EROFS, errno.EDQUOT,
})

# Substrings of hadoop-CLI stderr that mark a retryable condition
# (connection issues, HDFS safe mode, throttling) vs a semantic failure.
_TRANSIENT_MARKERS = (
    "connection refused", "connection reset", "connection timed out",
    "timed out", "timeout", "temporarily unavailable", "try again",
    "safe mode", "safemode", "socketexception", "sockettimeout",
    "broken pipe", "service unavailable", "slow down",
    "too many requests", "network is unreachable", "lease recovery",
    "could not obtain block", "retriableexception",
)
_PERMANENT_MARKERS = (
    "no such file", "file exists", "permission denied", "access denied",
    "is a directory", "not a directory", "invalid argument",
    "unsupported", "illegalargument", "filenotfound",
    "no space left", "disk quota exceeded", "quota exceeded",
    "read-only file system", "read only file system",
)


def is_transient(exc: BaseException) -> bool:
    """Classify an exception as transient (retryable) or permanent."""
    if isinstance(exc, TransientFSError):
        return True
    if isinstance(exc, PermanentFSError):
        return False
    if isinstance(exc, (FileNotFoundError, PermissionError,
                        IsADirectoryError, NotADirectoryError,
                        FileExistsError)):
        return False
    if isinstance(exc, (TimeoutError, ConnectionError)):
        return True
    if isinstance(exc, OSError):
        if exc.errno in _PERMANENT_ERRNOS:     # disk full / read-only
            return False
        return exc.errno in _TRANSIENT_ERRNOS
    return False


_retry_rng = random.Random()


def retry_call(op_name: str, fn, *args, **kwargs):
    """Run ``fn`` retrying transient failures: exponential backoff with
    jitter, bounded by ``FLAGS_fs_retry_times`` attempts and the
    ``FLAGS_fs_retry_deadline_s`` wall clock.  Non-transient errors and
    exhausted budgets re-raise the last (classified) error.  ``op_name``
    tags the per-op monitor stats (``fs.retries.<op>``) alongside the
    ``fs.retries``/``fs.gave_up`` aggregates."""
    from ..core import flags
    from . import monitor
    times = max(1, int(flags.get_flag("fs_retry_times")))
    base = float(flags.get_flag("fs_retry_backoff_s"))
    deadline = float(flags.get_flag("fs_retry_deadline_s"))
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            attempt += 1
            if not is_transient(e):
                if isinstance(e, OSError) and not isinstance(e, FSError) \
                        and e.errno in _PERMANENT_ERRNOS:
                    # surface the classification: callers (and the
                    # monitor) see an explicit PermanentFSError, not a
                    # bare OSError they might be tempted to retry
                    monitor.stat_add("fs.permanent")
                    monitor.stat_add(f"fs.permanent.{op_name}")
                    raise PermanentFSError(
                        f"fs.{op_name}: unrecoverable "
                        f"({errno.errorcode.get(e.errno, e.errno)}): {e}"
                    ) from e
                raise
            elapsed = time.monotonic() - start
            if attempt >= times or elapsed >= deadline:
                monitor.stat_add("fs.gave_up")
                monitor.stat_add(f"fs.gave_up.{op_name}")
                raise
            monitor.stat_add("fs.retries")
            monitor.stat_add(f"fs.retries.{op_name}")
            from ..core import obs_hook
            trc = obs_hook._tracer
            if trc is not None:
                trc.counter(f"fs.retries.{op_name}", 1)
            delay = min(base * (2 ** (attempt - 1)), 10.0)
            delay *= 1.0 + 0.25 * _retry_rng.random()      # jitter
            delay = min(delay, max(0.0, deadline - elapsed))
            if delay > 0:
                time.sleep(delay)


def retrying(op_name: str):
    """Decorator form of :func:`retry_call` for filesystem methods."""
    def deco(fn):
        def wrapped(*args, **kwargs):
            return retry_call(op_name, fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", op_name)
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco


class FileSystem:
    """Protocol: byte-level ops a checkpoint store needs (fs.h surface)."""

    def open_read(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def open_write(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        raise NotImplementedError

    def mv(self, src: str, dst: str) -> None:
        raise NotImplementedError


class LocalFS(FileSystem):
    """fs.cc localfs_*: plain files + atomic-rename mv."""

    def open_read(self, path):
        fault.point("fs.open_read", path)
        return open(path, "rb")

    def open_write(self, path):
        fault.point("fs.open_write", path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")

    def exists(self, path):
        fault.point("fs.exists", path)
        return os.path.exists(path)

    def mkdir(self, path):
        fault.point("fs.mkdir", path)
        os.makedirs(path, exist_ok=True)

    def remove(self, path):
        fault.point("fs.remove", path)
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def list(self, path):
        fault.point("fs.list", path)
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def mv(self, src, dst):
        fault.point("fs.mv", src, dst)
        os.replace(src, dst)


class RetryingFS(FileSystem):
    """Wrap any FileSystem with the transient-retry loop.  Registered
    schemes opt in via ``register_fs(scheme, fs, retry=True)``."""

    def __init__(self, inner: FileSystem):
        self.inner = inner

    def open_read(self, path):
        return retry_call("open_read", self.inner.open_read, path)

    def open_write(self, path):
        return retry_call("open_write", self.inner.open_write, path)

    def exists(self, path):
        return retry_call("exists", self.inner.exists, path)

    def mkdir(self, path):
        return retry_call("mkdir", self.inner.mkdir, path)

    def remove(self, path):
        return retry_call("remove", self.inner.remove, path)

    def list(self, path):
        return retry_call("list", self.inner.list, path)

    def mv(self, src, dst):
        return retry_call("mv", self.inner.mv, src, dst)


class PrefixStripFS(FileSystem):
    """Adapter mapping ``scheme://<path>`` onto an inner filesystem's
    plain paths — lets tests and chaos tools mount a LocalFS-backed dir
    under a registered scheme (e.g. ``flaky:///tmp/ckpt``)."""

    def __init__(self, inner: FileSystem, scheme: str):
        self.inner = inner
        self._prefix = scheme.rstrip(":/") + "://"

    def _p(self, path: str) -> str:
        if path.startswith(self._prefix):
            return path[len(self._prefix):]
        return path

    def open_read(self, path):
        return self.inner.open_read(self._p(path))

    def open_write(self, path):
        return self.inner.open_write(self._p(path))

    def exists(self, path):
        return self.inner.exists(self._p(path))

    def mkdir(self, path):
        return self.inner.mkdir(self._p(path))

    def remove(self, path):
        return self.inner.remove(self._p(path))

    def list(self, path):
        return self.inner.list(self._p(path))

    def mv(self, src, dst):
        return self.inner.mv(self._p(src), self._p(dst))


class ShellFS(FileSystem):
    """HDFS-style filesystem driven through a shell CLI (fs.cc hdfs_*:
    every op is ``{command} fs -<verb>``).  ``command`` defaults to the
    ``hadoop`` binary; AFS or other HDFS-compatible stores override it
    (the reference's HADOOP_HOME + ugi configs).

    Every op classifies CLI failures (transient net blips / safe mode /
    throttling vs semantic errors) and retries transient ones under the
    FLAGS_fs_retry_* budget — fs.cc's fs_retry_times analog.  A missing
    path is classified permanent, so :meth:`exists` answers False
    immediately instead of burning the retry budget."""

    def __init__(self, command: str = "hadoop"):
        self.command = command

    def _run_once(self, *args, input_bytes=None, capture=True):
        fault.point("fs.shell_run", self.command, *args)
        try:
            return subprocess.run(
                [self.command, "fs", *args], input=input_bytes,
                capture_output=capture, check=True)
        except FileNotFoundError as e:
            raise PermanentFSError(
                f"ShellFS: '{self.command}' CLI not found — install it or "
                f"register a different FileSystem for this scheme "
                f"(paddle_tpu.utils.fs.register_fs)") from e
        except subprocess.CalledProcessError as e:
            stderr = (e.stderr or b"").decode(errors="replace")
            msg = (f"ShellFS: {self.command} fs {' '.join(args)} failed "
                   f"(rc={e.returncode}): {stderr[:500]}")
            low = stderr.lower()
            if any(m in low for m in _PERMANENT_MARKERS):
                raise PermanentFSError(msg) from e
            if any(m in low for m in _TRANSIENT_MARKERS):
                raise TransientFSError(msg) from e
            # rc=1 with silent stderr is the CLI's semantic "false"
            # (-test on a missing path) — retrying cannot change it
            if e.returncode == 1 and not stderr.strip():
                raise PermanentFSError(msg) from e
            raise TransientFSError(msg) from e

    def _run(self, *args, input_bytes=None, capture=True):
        return retry_call("shell_run", self._run_once, *args,
                          input_bytes=input_bytes, capture=capture)

    def open_read(self, path):
        out = self._run("-cat", path)
        return io.BytesIO(out.stdout)

    def open_write(self, path):
        fs = self

        class _Buf(io.BytesIO):
            def close(self_inner):
                data = self_inner.getvalue()
                fs._run("-put", "-f", "-", path, input_bytes=data)
                super().close()

        return _Buf()

    def exists(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except PermanentFSError:
            return False

    def mkdir(self, path):
        self._run("-mkdir", "-p", path)

    def remove(self, path):
        self._run("-rm", "-r", "-f", path)

    def list(self, path):
        out = self._run("-ls", path).stdout.decode(errors="replace")
        names = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                names.append(parts[-1].rsplit("/", 1)[-1])
        return sorted(names)

    def mv(self, src, dst):
        # HDFS rename refuses to overwrite; emulate os.replace with a
        # delete-then-rename (weaker atomicity than LocalFS — the window
        # between rm and mv can leave no meta; readers treat a missing
        # meta as 'no checkpoint yet', which the resume path tolerates)
        try:
            self._run("-rm", "-f", dst)
        except FSError:
            pass
        try:
            self._run("-mv", src, dst)
        except FSError:
            # rename is NOT idempotent: a timed-out attempt may have
            # committed server-side, making the retry fail with 'no such
            # file' — verify the outcome before reporting failure
            try:
                if not self.exists(src) and self.exists(dst):
                    return
            except FSError:
                pass
            raise


_REGISTRY: Dict[str, FileSystem] = {}
_LOCAL = LocalFS()


def register_fs(scheme: str, fs: FileSystem, retry: bool = False) -> None:
    """Register a filesystem for a path scheme (``'hdfs'``, ``'s3'``...).

    ``retry=True`` wraps it in :class:`RetryingFS` so transient failures
    back off and retry under the FLAGS_fs_retry_* budget."""
    if retry:
        fs = RetryingFS(fs)
    _REGISTRY[scheme.rstrip(":/")] = fs


register_fs("hdfs", ShellFS("hadoop"))
register_fs("afs", ShellFS("hadoop"))


def get_fs(path: str) -> FileSystem:
    """fs_select_internal parity: pick the filesystem by path prefix."""
    if "://" in path:
        scheme = path.split("://", 1)[0]
        fs = _REGISTRY.get(scheme)
        if fs is None:
            raise ValueError(
                f"no FileSystem registered for scheme '{scheme}://' — "
                f"register one with paddle_tpu.utils.fs.register_fs")
        return fs
    return _LOCAL


def open_read(path: str):
    return get_fs(path).open_read(path)


def open_write(path: str):
    return get_fs(path).open_write(path)


def exists(path: str) -> bool:
    return get_fs(path).exists(path)


def write_atomic(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp-file + rename so a crash
    mid-write never leaves a truncated artifact (true atomicity on
    LocalFS os.replace; best-effort delete+rename on ShellFS)."""
    fs = get_fs(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with fs.open_write(tmp) as f:
        f.write(data)
    try:
        fs.mv(tmp, path)
    except BaseException:
        try:
            fs.remove(tmp)
        except Exception:
            pass
        raise
