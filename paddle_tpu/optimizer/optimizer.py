"""Optimizers.

TPU-native replacement for the reference's optimizer-op zoo (reference:
paddle/fluid/operators/optimizers/ — sgd_op, momentum_op, adam_op, lamb_op,
lars_momentum_op...; python façade python/paddle/optimizer/).

Design: every optimizer defines two PURE functions over arrays —
``init_slots`` and ``update_param`` — shared by:
- eager ``.step()`` (reads ``param.grad``, writes ``param.data``), and
- the jit path (``paddle_tpu.jit.TrainStep`` tree-maps them inside one
  compiled XLA program, where the whole update fuses into a handful of
  kernels — the analog of the reference's fused optimizer kernels).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.tensor import Parameter, Tensor
from .clip import ClipGradBase
from .lr import LRScheduler
from .regularizer import L1Decay, L2Decay, WeightDecayRegularizer


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups: flatten (kept simple; per-group lr TODO)
                flat = []
                for grp in parameters:
                    flat.extend(grp["params"])
                parameters = flat
        self._parameter_list: Optional[List[Parameter]] = parameters
        self._learning_rate = learning_rate
        self._grad_clip: Optional[ClipGradBase] = grad_clip
        if isinstance(weight_decay, (int, float)):
            weight_decay = L2Decay(float(weight_decay))
        self._weight_decay: Optional[WeightDecayRegularizer] = weight_decay
        self._multi_precision = multi_precision
        self._slots: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when learning rate is an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return (self._learning_rate
                if isinstance(self._learning_rate, LRScheduler) else None)

    # -- pure per-param update (override these two) -----------------------
    def init_slots(self, p: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        return {}

    def update_param(self, p, g, slots, lr, step):
        raise NotImplementedError

    # -- regularization ----------------------------------------------------
    def _apply_decay(self, param: Parameter, g):
        """Param-level regularizer wins over optimizer-level
        (reference: fluid/regularizer.py append_regularization_ops)."""
        reg = getattr(param, "regularizer", None) or self._weight_decay
        if reg is not None and not self._decoupled():
            g = reg(param.data, g)
        return g

    def _decoupled(self) -> bool:
        return False  # AdamW overrides

    def _decoupled_decay(self, p, lr, param_name=None):
        """Decoupled (AdamW-style) decay applied to the param array right
        before the main update; base optimizers are a no-op."""
        return p

    def _param_lr_ratio(self, param) -> float:
        return 1.0  # AdamW lr_ratio overrides

    # -- eager step --------------------------------------------------------
    def step(self):
        assert self._parameter_list is not None, (
            "optimizer constructed without parameters; pass parameters= "
            "or use the functional interface")
        self._step_count += 1
        # clip raw grads first, THEN regularize — matching the reference's
        # apply_gradients order (python/paddle/optimizer/optimizer.py:746-757)
        # and this file's functional_update.
        from ..core.selected_rows import SelectedRows
        pg = [(p, p._grad_data) for p in self._parameter_list
              if p.trainable and p._grad_data is not None]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)  # SelectedRows-aware (clip.py)
        # weight decay skips SelectedRows (regularizing only touched rows
        # would bias the decay; the reference's sparse tables decay via
        # table-side accessors instead)
        pg = [(p, g if isinstance(g, SelectedRows)
               else self._apply_decay(p, g)) for p, g in pg]
        lr = self.get_lr()
        for p, g in pg:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self.init_slots(p.data)
                if (self._multi_precision
                        and p.data.dtype in (jnp.bfloat16, jnp.float16)):
                    slots["master"] = p.data.astype(jnp.float32)
                self._slots[id(p)] = slots
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            plr = plr * self._param_lr_ratio(p)
            if isinstance(g, SelectedRows):
                g = g.merge()
                if "master" not in slots and self._sparse_supported():
                    # true SelectedRows semantics: only touched rows (and
                    # their optimizer slots) are updated
                    p.data, new_slots = self._sparse_update_param(
                        p.data, g, slots, plr, self._step_count)
                    self._slots[id(p)] = new_slots
                    continue
                g = g.to_dense()  # optimizers without a sparse kernel
            if "master" in slots:
                master = self._decoupled_decay(slots["master"], plr, p.name)
                new_master, new_slots = self.update_param(
                    master, g.astype(jnp.float32),
                    {k: v for k, v in slots.items() if k != "master"},
                    plr, self._step_count)
                new_slots["master"] = new_master
                p.data = new_master.astype(p.data.dtype)
            else:
                pdata = self._decoupled_decay(p.data, plr, p.name)
                p.data, new_slots = self.update_param(
                    pdata, g, slots, plr, self._step_count)
            self._slots[id(p)] = new_slots

    # -- sparse (SelectedRows) updates -------------------------------------
    def _sparse_supported(self) -> bool:
        """Whether this optimizer has a row-wise SelectedRows kernel
        (reference: sgd_op.h SelectedRows branch, adam_op.h lazy_mode)."""
        return False

    def _sparse_update_param(self, p, sr, slots, lr, step):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Static mode: attach this optimizer to the loss's Program
        (static.Executor compiles backward + update in-graph).  Eager:
        backward + step (reference: optimizer.py minimize)."""
        from ..static.program import Variable
        if isinstance(loss, Variable):
            loss.program._optimizer = (self, loss, parameters, no_grad_set)
            return None, None
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameter_list or [])]

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- functional interface (used by jit.TrainStep) ----------------------
    def functional_init(self, param_arrays: Sequence[jnp.ndarray]):
        states = []
        for p in param_arrays:
            s = self.init_slots(p)
            if (self._multi_precision
                    and p.dtype in (jnp.bfloat16, jnp.float16)):
                s["master"] = p.astype(jnp.float32)
            states.append(s)
        return states

    def functional_update(self, param_arrays, grad_arrays, states, lr,
                          step, params_meta=None):
        """Pure: returns (new_params, new_states). ``lr``/``step`` may be
        traced scalars.  params_meta: optional list of Parameters for
        regularizer / per-param lr metadata."""
        meta = params_meta or [None] * len(param_arrays)
        if self._grad_clip is not None:
            pg = self._grad_clip(list(zip(meta, grad_arrays)))
            grad_arrays = [g for _, g in pg]
        new_ps, new_ss = [], []
        for p, g, s, m in zip(param_arrays, grad_arrays, states, meta):
            if m is not None:
                reg = getattr(m, "regularizer", None) or self._weight_decay
                if reg is not None and not self._decoupled():
                    g = reg(p, g)
                plr = lr * getattr(m, "optimize_attr", {}).get("learning_rate", 1.0)
                plr = plr * self._param_lr_ratio(m)
            elif self._weight_decay is not None and not self._decoupled():
                g = self._weight_decay(p, g)
                plr = lr
            else:
                plr = lr
            pname = m.name if m is not None else None
            if "master" in s:
                sub = {k: v for k, v in s.items() if k != "master"}
                master = self._decoupled_decay(s["master"], plr, pname)
                new_master, ns = self.update_param(
                    master, g.astype(jnp.float32), sub, plr, step)
                ns["master"] = new_master
                new_ps.append(new_master.astype(p.dtype))
            else:
                p_in = self._decoupled_decay(p, plr, pname)
                np_, ns = self.update_param(p_in, g, s, plr, step)
                new_ps.append(np_)
            new_ss.append(ns)
        return new_ps, new_ss

    # -- state dict --------------------------------------------------------
    def _effective_step(self):
        """Applied-update count.  A compiled TrainStep tracks this on
        device (skipped non-finite steps don't advance it); fall back to
        the host counter otherwise."""
        step = getattr(self, "_bound_train_step", None)
        aux = getattr(step, "_scaler_state", None)
        if aux and "step" in aux:
            return int(aux["step"])
        return self._step_count

    def state_dict(self):
        out = {"step": self._effective_step(), "slots": {}}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                s = self._slots.get(id(p))
                if s:
                    out["slots"][str(i)] = {k: np.asarray(v)
                                            for k, v in s.items()}
        # static path: slots live in the Executor's device-resident
        # state, not in self._slots — read them through the provider the
        # Executor registered (keys are positions in
        # program.parameters(); set_state_dict routes them back via
        # _static_pending_slots).  Only when no eager slots exist: the
        # two index spaces (parameter_list vs program.parameters())
        # differ, and a mixed eager+static optimizer checkpoint would
        # silently cross-wire moments — eager slots win, as before.
        prov = getattr(self, "_static_state_provider", None)
        if prov is not None and not out["slots"]:
            st = prov()
            if st is not None:
                out["slots"].update(st.export_slots())
        if self._lr_scheduler is not None:
            out["lr_scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("step", 0)
        # resync any compiled TrainStep: preserve its in-graph scaler
        # values, then drop the aux carry so the next step reinitialises
        # from the newly loaded counters
        step = getattr(self, "_bound_train_step", None)
        if step is not None:
            if step.scaler is not None:
                step.scaler._sync_from_bound_step()
            step._scaler_state = None
        slots = state.get("slots", {})
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                if str(i) in slots:
                    self._slots[id(p)] = {
                        k: jnp.asarray(v) for k, v in slots[str(i)].items()}
        elif slots:
            # static path (no parameter list): slot keys are positions in
            # program.parameters().  Stash them for the Executor to load
            # into its device-resident state, and drop any live state's
            # slots so the next run reinitialises from the checkpoint
            self._static_pending_slots = dict(slots)
            prov = getattr(self, "_static_state_provider", None)
            st = prov() if prov is not None else None
            if st is not None:
                st.opt_state = None
        if self._lr_scheduler is not None and "lr_scheduler" in state:
            self._lr_scheduler.set_state_dict(state["lr_scheduler"])


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc."""

    def update_param(self, p, g, slots, lr, step):
        return p - lr * g.astype(p.dtype), slots

    def _sparse_supported(self):
        return True

    def _sparse_update_param(self, p, sr, slots, lr, step):
        """Row-wise scatter update (reference: sgd_op.h SelectedRows
        kernel): untouched rows are never read or written."""
        return p.at[sr.rows].add(-lr * sr.values.astype(p.dtype)), slots


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.h."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(
            p, dtype=jnp.float32 if self._multi_precision else p.dtype)}

    def update_param(self, p, g, slots, lr, step):
        g = g.astype(p.dtype)
        v = self._momentum * slots["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.h."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lazy = bool(lazy_mode)

    def _sparse_supported(self):
        return self._lazy

    def _sparse_update_param(self, p, sr, slots, lr, step):
        """lazy_mode Adam (reference: adam_op.h lazy_mode branch): moments
        and params update ONLY on touched rows; untouched rows keep stale
        moments — the documented lazy semantics for huge embeddings."""
        b1, b2, eps = self._beta1, self._beta2, self._eps
        rows = sr.rows
        g = sr.values.astype(slots["m"].dtype)
        m_r = b1 * slots["m"][rows] + (1 - b1) * g
        v_r = b2 * slots["v"][rows] + (1 - b2) * g * g
        step_f = jnp.asarray(step, jnp.float32)
        mhat = m_r / (1 - b1 ** step_f)
        vhat = v_r / (1 - b2 ** step_f)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        return (p.at[rows].add(-upd.astype(p.dtype)),
                {"m": slots["m"].at[rows].set(m_r),
                 "v": slots["v"].at[rows].set(v_r)})

    def init_slots(self, p):
        dt = jnp.float32 if p.dtype in (jnp.bfloat16, jnp.float16) else p.dtype
        return {"m": jnp.zeros_like(p, dtype=dt),
                "v": jnp.zeros_like(p, dtype=dt)}

    def update_param(self, p, g, slots, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        g = g.astype(slots["m"].dtype)
        m = b1 * slots["m"] + (1 - b1) * g
        v = b2 * slots["v"] + (1 - b2) * g * g
        # bias correction with traced-friendly power
        step_f = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** step_f)
        vhat = v / (1 - b2 ** step_f)
        upd = lr * mhat / (jnp.sqrt(vhat) + eps)
        return (p - upd.astype(p.dtype)), {"m": m, "v": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: adamw — python/paddle/optimizer/
    adamw.py; decay applied directly to the param, not the grad)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = (weight_decay.coeff
                       if isinstance(weight_decay, L2Decay)
                       else float(weight_decay))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decoupled(self):
        return True

    def _param_lr_ratio(self, param):
        if self._lr_ratio is None or param is None:
            return 1.0
        return float(self._lr_ratio(param))

    def _decoupled_decay(self, p, lr, param_name=None):
        fn = self._apply_decay_param_fun
        if fn is not None and param_name is not None and not fn(param_name):
            return p
        return p - lr * self._coeff * p


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_slots(self, p):
        return {"m": jnp.zeros_like(p), "inf": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * slots["m"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf"], jnp.abs(g))
        step_f = jnp.asarray(step, jnp.float32)
        new_p = p - (lr / (1 - b1 ** step_f)) * m / (u + eps)
        return new_p, {"m": m, "inf": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def update_param(self, p, g, slots, lr, step):
        mom = slots["moment"] + g * g
        return p - lr * g / (jnp.sqrt(mom) + self._eps), {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps, self._rho = epsilon, rho

    def init_slots(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p),
                "avg_sq_update": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        rho, eps = self._rho, self._eps
        asg = rho * slots["avg_sq_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(slots["avg_sq_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * slots["avg_sq_update"] + (1 - rho) * upd * upd
        return p - lr * upd, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p),
             "momentum": jnp.zeros_like(p)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def update_param(self, p, g, slots, lr, step):
        rho, eps = self._rho, self._eps
        ms = rho * slots["mean_square"] + (1 - rho) * g * g
        out = dict(slots, mean_square=ms)
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        out["momentum"] = mom
        return p - mom, out


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.h (large-batch)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_slots(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * slots["m"] + (1 - b1) * g
        v = b2 * slots["v"] + (1 - b2) * g * g
        step_f = jnp.asarray(step, jnp.float32)
        mhat = m / (1 - b1 ** step_f)
        vhat = v / (1 - b2 ** step_f)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"m": m, "v": v}


class LarsMomentum(Optimizer):
    """reference: operators/optimizers/lars_momentum_op.cc."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def init_slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * w_norm
            / (g_norm + self._lars_wd * w_norm + self._eps), lr)
        v = (self._momentum * slots["velocity"]
             + local_lr * (g + self._lars_wd * p))
        return p - v, {"velocity": v}


class Ftrl(Optimizer):
    """reference: operators/optimizers/ftrl_op.h (FTRL-Proximal,
    McMahan et al.; linear/squared accumulators, soft-threshold on the
    linear term).  ``lr_power`` follows the reference's sign convention
    (-0.5 means accum^0.5 in the denominators)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0,
                 lr_power=-0.5, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def init_slots(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        sq, lin = slots["squared"], slots["linear"]
        new_sq = sq + g * g
        if self._lr_power == -0.5:
            sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
            denom = jnp.sqrt(new_sq) / lr
        else:
            sigma = (new_sq ** -self._lr_power
                     - sq ** -self._lr_power) / lr
            denom = new_sq ** -self._lr_power / lr
        new_lin = lin + g - sigma * p
        x = self._l1 * jnp.sign(new_lin) - new_lin
        y = denom + 2.0 * self._l2
        new_p = jnp.where(jnp.abs(new_lin) > self._l1, x / y,
                          jnp.zeros_like(p))
        return new_p, {"squared": new_sq, "linear": new_lin}


class Dpsgd(Optimizer):
    """reference: operators/optimizers/dpsgd_op.h — differentially
    private SGD: whole-gradient L2 clip to ``clip`` plus one shared
    Gaussian noise draw scaled by 1/batch_size.

    Divergence (documented): the reference seeds from time() when
    seed==0, which cannot exist inside a compiled step — seed=0 here is
    simply the literal seed, with the step index folded in so every
    step draws fresh noise."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, seed=0, parameters=None, name=None):
        super().__init__(learning_rate, parameters, None, None, name)
        self._clip, self._batch = clip, batch_size
        self._sigma, self._seed = sigma, seed
        self._next_noise_id = 0

    def init_slots(self, p):
        # per-parameter noise id: the Gaussian-mechanism analysis needs
        # INDEPENDENT noise per tensor — a (seed, step)-only key would
        # hand every parameter the same draw
        nid = self._next_noise_id
        self._next_noise_id += 1
        return {"noise_id": jnp.asarray(nid, jnp.int32)}

    def update_param(self, p, g, slots, lr, step):
        norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        scale = jnp.where(norm > self._clip, norm / self._clip, 1.0)
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 jnp.asarray(step, jnp.int32))
        key = jax.random.fold_in(key, slots["noise_id"])
        noise = self._sigma * jax.random.normal(key, (), jnp.float32)
        upd = g / scale.astype(g.dtype) + (noise / self._batch).astype(
            g.dtype)
        return p - lr * upd, {"noise_id": slots["noise_id"]}


class ProximalGD(Optimizer):
    """reference: operators/optimizers/proximal_gd_op.h — plain GD step
    followed by the L1 soft-threshold / L2 shrink proximal map."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._l1, self._l2 = l1, l2

    def init_slots(self, p):
        return {}

    def _prox(self, prox_param, lr):
        if self._l1 > 0:
            return (jnp.sign(prox_param)
                    * jnp.maximum(jnp.abs(prox_param) - lr * self._l1, 0.0)
                    / (1.0 + lr * self._l2))
        return prox_param / (1.0 + lr * self._l2)

    def update_param(self, p, g, slots, lr, step):
        return self._prox(p - lr * g, lr), slots


class ProximalAdagrad(ProximalGD):
    """reference: operators/optimizers/proximal_adagrad_op.h — Adagrad
    step (accumulated g^2 scaling) followed by the same proximal map.

    Divergence (documented): the reference divides by sqrt(moment) with
    no epsilon, so an element whose accumulated g^2 is still zero (dead
    unit, untouched row) becomes 0/0 = NaN and is destroyed; here a
    zero accumulator takes a zero step instead."""

    def init_slots(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        mom = slots["moment"] + g * g
        safe = jnp.where(mom > 0, mom, 1.0)
        step_v = jnp.where(mom > 0, lr * g / jnp.sqrt(safe), 0.0)
        return self._prox(p - step_v, lr), {"moment": mom}


class DecayedAdagrad(Optimizer):
    """reference: operators/optimizers/decayed_adagrad_op.h — Adagrad
    with an exponentially decayed accumulator."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._decay, self._eps = decay, epsilon

    def init_slots(self, p):
        return {"moment": jnp.zeros_like(p)}

    def update_param(self, p, g, slots, lr, step):
        mom = self._decay * slots["moment"] + (1 - self._decay) * g * g
        return p - lr * g / (jnp.sqrt(mom) + self._eps), {"moment": mom}
